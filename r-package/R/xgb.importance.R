# xgb.importance — feature importance from the model dump
# (reference surface: R-package/R/xgb.importance.R; computed R-side from
# xgb.dump(with_stats = TRUE) text, the same source the reference parses).

#' Per-feature Gain / Cover / Frequency importance.
#'
#' @param model an xgb.Booster.
#' @param feature_names optional names; defaults to f0..fN ids from the dump.
#' @return data.frame sorted by Gain share (columns sum to 1).
xgb.importance <- function(model, feature_names = NULL) {
  dump <- xgb.dump(model, with_stats = TRUE, dump_format = "text")
  lines <- unlist(strsplit(dump, "\n"), use.names = FALSE)
  splits <- grep("\\[f[0-9]+[<]", lines, value = TRUE)
  feat <- sub("^.*\\[(f[0-9]+)[<].*$", "\\1", splits)
  gain <- as.numeric(sub("^.*gain=([-0-9.eE+]+).*$", "\\1", splits))
  cover <- as.numeric(sub("^.*cover=([-0-9.eE+]+).*$", "\\1", splits))
  if (length(feat) == 0)
    return(data.frame(Feature = character(), Gain = numeric(),
                      Cover = numeric(), Frequency = numeric()))
  agg_g <- tapply(gain, feat, sum)
  agg_c <- tapply(cover, feat, sum)
  agg_f <- table(feat)
  nm <- names(agg_g)
  if (!is.null(feature_names)) {
    ids <- as.integer(sub("^f", "", nm)) + 1L
    nm <- feature_names[ids]
  }
  out <- data.frame(Feature = nm,
                    Gain = as.numeric(agg_g) / sum(agg_g),
                    Cover = as.numeric(agg_c) / sum(agg_c),
                    Frequency = as.numeric(agg_f) / sum(agg_f))
  out[order(-out$Gain), , drop = FALSE]
}
