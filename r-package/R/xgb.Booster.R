# xgb.train / predict / save / load — the reference R training surface
# (R-package/R/xgb.train.R, xgb.Booster.R) over the xtb C ABI.

#' Parse one "[i]\tname-metric:value\t..." eval line into a named numeric
#' vector (names like "train-logloss") — shared by xgb.train early stopping
#' and xgb.cv's per-fold aggregation.
xgb.parse.eval <- function(msg) {
  toks <- strsplit(sub("^\\[[0-9]+\\]\\s*", "", msg), "[\t ]+")[[1]]
  toks <- toks[nzchar(toks)]
  kv <- regmatches(toks, regexpr(":", toks), invert = TRUE)
  vals <- vapply(kv, function(p) as.numeric(p[2]), numeric(1))
  names(vals) <- vapply(kv, function(p) p[1], character(1))
  vals
}

#' TRUE when a metric name means "bigger is better" (reference:
#' R-package/R/callbacks.R early-stop maximize auto-detection; mape is the
#' error metric the "map" prefix must NOT capture).
xgb.metric.maximize <- function(metric) {
  m <- sub("^.*-", "", metric)
  grepl("^(auc|aucpr|map|ndcg|pre)", m) && !grepl("^mape", m)
}

#' One early-stopping step, shared by xgb.train and xgb.cv.
#' state: list(best_score, best_iter); returns the updated state with
#' $stop = TRUE once `rounds` iterations passed without improvement.
xgb.early.stop.update <- function(state, score, metric_name, i, rounds,
                                  maximize = NULL) {
  mx <- if (is.null(maximize)) xgb.metric.maximize(metric_name) else maximize
  better <- is.na(state$best_score) ||
    (if (mx) score > state$best_score else score < state$best_score)
  if (better) {
    state$best_score <- score
    state$best_iter <- i
  }
  state$stop <- !better && (i - state$best_iter >= rounds)
  state
}

#' Train a gradient-boosted model.
#'
#' @param params named list of booster parameters
#'   (e.g. list(objective = "binary:logistic", max_depth = 4, eta = 0.3)).
#' @param data an xgb.DMatrix.
#' @param nrounds number of boosting rounds.
#' @param evals named list of xgb.DMatrix to evaluate each round.
#' @param verbose print eval lines when TRUE.
#' @param early_stopping_rounds stop when the LAST metric on the LAST evals
#'   entry has not improved for this many rounds (reference semantics:
#'   xgb.train.R early stopping on the final watchlist member).  The best
#'   round lands in $best_iteration / $best_score (1-based, R convention)
#'   and in the "best_iteration" booster attr (0-based round id — the
#'   cross-language attr convention shared with the Python package, so
#'   attr-driven consumers like ntreelimit agree across bindings).
#' @param maximize direction for early stopping; NULL auto-detects from the
#'   metric name (auc/map/ndcg/pre maximize, everything else minimizes).
xgb.train <- function(params = list(), data, nrounds = 10,
                      evals = list(), verbose = TRUE,
                      early_stopping_rounds = NULL, maximize = NULL) {
  stopifnot(inherits(data, "xgb.DMatrix"))
  if (length(evals) > 0 &&
      (is.null(names(evals)) || any(names(evals) == "")))
    stop("evals must be a fully named list, e.g. list(train = dtrain)")
  if (!is.null(early_stopping_rounds) && length(evals) == 0)
    stop("early_stopping_rounds needs at least one evals entry")
  dmats <- c(list(data), unname(evals))
  handle <- .Call(XTBBoosterCreate_R, lapply(dmats, function(d) d$handle))
  for (nm in names(params))
    .Call(XTBBoosterSetParam_R, handle, nm, as.character(params[[nm]]))
  bst <- structure(list(handle = handle, params = params,
                        nrounds = nrounds),
                   class = "xgb.Booster")
  eval_names <- names(evals)
  log <- list()
  es <- list(best_score = NA_real_, best_iter = -1L, stop = FALSE)
  for (i in seq_len(nrounds) - 1L) {
    .Call(XTBBoosterUpdateOneIter_R, handle, i, data$handle)
    if (length(evals) > 0) {
      msg <- .Call(XTBBoosterEvalOneIter_R, handle, i,
                   lapply(unname(evals), function(d) d$handle), eval_names)
      if (isTRUE(verbose)) message(msg)
      vals <- xgb.parse.eval(msg)
      log[[length(log) + 1L]] <- vals
      if (!is.null(early_stopping_rounds)) {
        es <- xgb.early.stop.update(es, vals[[length(vals)]],
                                    names(vals)[length(vals)], i,
                                    early_stopping_rounds, maximize)
        if (es$stop) {
          if (isTRUE(verbose))
            message(sprintf("early stop: best round %d (%s = %g)",
                            es$best_iter + 1L, names(vals)[length(vals)],
                            es$best_score))
          break
        }
      }
    }
  }
  if (length(log) > 0)
    bst$evaluation_log <- do.call(rbind, log)
  if (es$best_iter >= 0L) {
    bst$best_iteration <- es$best_iter + 1L
    bst$best_score <- es$best_score
    .Call(XTBBoosterSetAttr_R, handle, "best_iteration",
          as.character(es$best_iter))
    .Call(XTBBoosterSetAttr_R, handle, "best_score",
          as.character(es$best_score))
  }
  bst
}

#' Read a booster attribute set during training (e.g. "best_iteration").
xgb.attr <- function(model, name) {
  .Call(XTBBoosterGetAttr_R, model$handle, name)
}

#' @export
predict.xgb.Booster <- function(object, newdata, outputmargin = FALSE,
                                ntreelimit = 0, ...) {
  if (!inherits(newdata, "xgb.DMatrix")) newdata <- xgb.DMatrix(newdata)
  mask <- if (isTRUE(outputmargin)) 1L else 0L
  .Call(XTBBoosterPredict_R, object$handle, newdata$handle, mask,
        as.integer(ntreelimit), 0L)
}

#' Save a model to JSON/UBJSON (by file extension).
xgb.save <- function(model, fname) {
  .Call(XTBBoosterSaveModel_R, model$handle, fname)
  invisible(TRUE)
}

#' Load a model from file.
xgb.load <- function(fname) {
  handle <- .Call(XTBBoosterCreate_R, list())
  .Call(XTBBoosterLoadModel_R, handle, fname)
  structure(list(handle = handle, params = list()), class = "xgb.Booster")
}

#' Serialize a model to a raw vector ("json" or "ubj").
xgb.save.raw <- function(model, raw_format = "ubj") {
  .Call(XTBBoosterSaveModelToRaw_R, model$handle, raw_format)
}

#' Restore a model from a raw vector.
xgb.load.raw <- function(raw) {
  handle <- .Call(XTBBoosterCreate_R, list())
  .Call(XTBBoosterLoadModelFromRaw_R, handle, raw)
  structure(list(handle = handle, params = list()), class = "xgb.Booster")
}

#' Dump the trees as text or json strings.
xgb.dump <- function(model, with_stats = FALSE, dump_format = "text") {
  .Call(XTBBoosterDumpModel_R, model$handle, "", as.integer(with_stats),
        dump_format)
}

#' @export
print.xgb.Booster <- function(x, ...) {
  cat("xgboost.tpu booster,", length(xgb.dump(x)), "trees\n")
  invisible(x)
}
