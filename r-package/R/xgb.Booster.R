# xgb.train / predict / save / load — the reference R training surface
# (R-package/R/xgb.train.R, xgb.Booster.R) over the xtb C ABI.

#' Train a gradient-boosted model.
#'
#' @param params named list of booster parameters
#'   (e.g. list(objective = "binary:logistic", max_depth = 4, eta = 0.3)).
#' @param data an xgb.DMatrix.
#' @param nrounds number of boosting rounds.
#' @param evals named list of xgb.DMatrix to evaluate each round.
#' @param verbose print eval lines when TRUE.
xgb.train <- function(params = list(), data, nrounds = 10,
                      evals = list(), verbose = TRUE) {
  stopifnot(inherits(data, "xgb.DMatrix"))
  if (length(evals) > 0 &&
      (is.null(names(evals)) || any(names(evals) == "")))
    stop("evals must be a fully named list, e.g. list(train = dtrain)")
  dmats <- c(list(data), unname(evals))
  handle <- .Call(XTBBoosterCreate_R, lapply(dmats, function(d) d$handle))
  for (nm in names(params))
    .Call(XTBBoosterSetParam_R, handle, nm, as.character(params[[nm]]))
  bst <- structure(list(handle = handle, params = params,
                        nrounds = nrounds),
                   class = "xgb.Booster")
  eval_names <- names(evals)
  for (i in seq_len(nrounds) - 1L) {
    .Call(XTBBoosterUpdateOneIter_R, handle, i, data$handle)
    if (length(evals) > 0) {
      msg <- .Call(XTBBoosterEvalOneIter_R, handle, i,
                   lapply(unname(evals), function(d) d$handle), eval_names)
      if (isTRUE(verbose)) message(msg)
    }
  }
  bst
}

#' @export
predict.xgb.Booster <- function(object, newdata, outputmargin = FALSE,
                                ntreelimit = 0, ...) {
  if (!inherits(newdata, "xgb.DMatrix")) newdata <- xgb.DMatrix(newdata)
  mask <- if (isTRUE(outputmargin)) 1L else 0L
  .Call(XTBBoosterPredict_R, object$handle, newdata$handle, mask,
        as.integer(ntreelimit), 0L)
}

#' Save a model to JSON/UBJSON (by file extension).
xgb.save <- function(model, fname) {
  .Call(XTBBoosterSaveModel_R, model$handle, fname)
  invisible(TRUE)
}

#' Load a model from file.
xgb.load <- function(fname) {
  handle <- .Call(XTBBoosterCreate_R, list())
  .Call(XTBBoosterLoadModel_R, handle, fname)
  structure(list(handle = handle, params = list()), class = "xgb.Booster")
}

#' Serialize a model to a raw vector ("json" or "ubj").
xgb.save.raw <- function(model, raw_format = "ubj") {
  .Call(XTBBoosterSaveModelToRaw_R, model$handle, raw_format)
}

#' Restore a model from a raw vector.
xgb.load.raw <- function(raw) {
  handle <- .Call(XTBBoosterCreate_R, list())
  .Call(XTBBoosterLoadModelFromRaw_R, handle, raw)
  structure(list(handle = handle, params = list()), class = "xgb.Booster")
}

#' Dump the trees as text or json strings.
xgb.dump <- function(model, with_stats = FALSE, dump_format = "text") {
  .Call(XTBBoosterDumpModel_R, model$handle, "", as.integer(with_stats),
        dump_format)
}

#' @export
print.xgb.Booster <- function(x, ...) {
  cat("xgboost.tpu booster,", length(xgb.dump(x)), "trees\n")
  invisible(x)
}
