# xgb.cv — k-fold cross-validation over the C ABI
# (reference surface: R-package/R/xgb.cv.R; implementation is fresh — fold
# DMatrices come from XGDMatrixSliceDMatrix so meta info rides along).

#' K-fold cross-validation.
#'
#' @param params booster parameters (see xgb.train).
#' @param data an xgb.DMatrix carrying labels (and any weights/margins).
#' @param nrounds boosting rounds per fold.
#' @param nfold number of folds.
#' @param stratified stratify folds by label (classification); default
#'   stratifies when the objective name contains "logistic" or "softmax"/
#'   "softprob", matching the reference's heuristic.
#' @param folds optional explicit list of validation-row index vectors
#'   (1-based); overrides nfold/stratified.
#' @param metrics optional extra eval metrics (character vector; each is
#'   appended via SetParam("eval_metric", ...) — the last one drives early
#'   stopping).
#' @param early_stopping_rounds stop all folds when the mean test metric
#'   has not improved for this many rounds.
#' @param maximize direction for early stopping (NULL = auto from name).
#' @param verbose print the aggregated eval line each round.
#' @return list with $evaluation_log (mean/std per round), $folds, and
#'   $best_iteration when early stopping fired.
xgb.cv <- function(params = list(), data, nrounds = 10, nfold = 5,
                   stratified = NULL, folds = NULL, metrics = NULL,
                   early_stopping_rounds = NULL, maximize = NULL,
                   verbose = TRUE) {
  stopifnot(inherits(data, "xgb.DMatrix"))
  n <- xgb.DMatrix.num.row(data)
  if (is.null(folds)) {
    if (is.null(stratified)) {
      obj <- if (is.null(params$objective)) "" else params$objective
      stratified <- grepl("logistic|softmax|softprob", obj)
    }
    if (stratified) {
      y <- getinfo(data, "label")
      # sample.int, NOT sample(x): a single-row class would otherwise hit
      # R's length-1 sample() expansion and corrupt the fold indices
      idx <- unlist(lapply(split(seq_len(n), y),
                           function(x) x[sample.int(length(x))]),
                    use.names = FALSE)
    } else {
      idx <- sample.int(n)
    }
    folds <- split(idx, rep_len(seq_len(nfold), n))
  }
  sessions <- lapply(folds, function(test_idx) {
    train_idx <- setdiff(seq_len(n), test_idx)
    dtrain <- xgb.slice.DMatrix(data, train_idx)
    dtest <- xgb.slice.DMatrix(data, test_idx)
    handle <- .Call(XTBBoosterCreate_R, list(dtrain$handle, dtest$handle))
    for (nm in names(params))
      .Call(XTBBoosterSetParam_R, handle, nm, as.character(params[[nm]]))
    # repeated SetParam("eval_metric", ...) appends (ABI contract)
    for (m in metrics)
      .Call(XTBBoosterSetParam_R, handle, "eval_metric", as.character(m))
    list(handle = handle, dtrain = dtrain, dtest = dtest)
  })
  log <- list()
  es <- list(best_score = NA_real_, best_iter = -1L, stop = FALSE)
  for (i in seq_len(nrounds) - 1L) {
    per_fold <- lapply(sessions, function(s) {
      .Call(XTBBoosterUpdateOneIter_R, s$handle, i, s$dtrain$handle)
      xgb.parse.eval(.Call(XTBBoosterEvalOneIter_R, s$handle, i,
                           list(s$dtrain$handle, s$dtest$handle),
                           c("train", "test")))
    })
    m <- do.call(rbind, per_fold)
    row <- c(apply(m, 2, mean), apply(m, 2, stats::sd))
    names(row) <- c(paste0(colnames(m), "_mean"),
                    paste0(colnames(m), "_std"))
    log[[length(log) + 1L]] <- row
    if (isTRUE(verbose))
      message(sprintf("[%d]\t%s", i, paste(
        sprintf("%s:%.6f", names(row), row), collapse = "\t")))
    if (!is.null(early_stopping_rounds)) {
      test_cols <- grep("^test-.*_mean$", names(row))
      metric_name <- sub("_mean$", "",
                         names(row)[test_cols[length(test_cols)]])
      es <- xgb.early.stop.update(es, row[[test_cols[length(test_cols)]]],
                                  metric_name, i, early_stopping_rounds,
                                  maximize)
      if (es$stop) {
        if (isTRUE(verbose))
          message(sprintf("early stop: best round %d", es$best_iter + 1L))
        break
      }
    }
  }
  out <- list(evaluation_log = do.call(rbind, log), folds = folds,
              params = params)
  if (es$best_iter >= 0L) {
    out$best_iteration <- es$best_iter + 1L
    out$best_score <- es$best_score
  }
  class(out) <- "xgb.cv.synchronous"
  out
}

#' @export
print.xgb.cv.synchronous <- function(x, ...) {
  cat("xgboost.tpu cv,", nrow(x$evaluation_log), "rounds,",
      length(x$folds), "folds\n")
  invisible(x)
}
