# xgb.DMatrix — R-side data container (reference surface:
# R-package/R/xgb.DMatrix.R; implementation is fresh over the xtb C ABI).

#' Construct an xgb.DMatrix from a numeric matrix.
#'
#' @param data numeric matrix (rows = examples).  NA marks missing.
#' @param label optional numeric label vector.
#' @param weight optional per-row weight vector.
#' @param base_margin optional per-row starting margin.
#' @param group optional query-group sizes for ranking.
#' @param missing value to treat as missing (default NA).
xgb.DMatrix <- function(data, label = NULL, weight = NULL,
                        base_margin = NULL, group = NULL, missing = NA) {
  if (!is.matrix(data)) data <- as.matrix(data)
  storage.mode(data) <- "double"
  handle <- .Call(XTBDMatrixCreateFromMat_R, data, as.numeric(missing))
  dmat <- structure(list(handle = handle), class = "xgb.DMatrix")
  if (!is.null(label))
    .Call(XTBDMatrixSetInfo_R, handle, "label", as.numeric(label))
  if (!is.null(weight))
    .Call(XTBDMatrixSetInfo_R, handle, "weight", as.numeric(weight))
  if (!is.null(base_margin))
    .Call(XTBDMatrixSetInfo_R, handle, "base_margin",
          as.numeric(base_margin))
  if (!is.null(group))
    .Call(XTBDMatrixSetInfo_R, handle, "group", as.numeric(group))
  dmat
}

#' Set a meta-info field on an xgb.DMatrix after construction
#' (reference surface: R-package/R/xgb.DMatrix.R setinfo).
#' Supported fields: label, weight, base_margin, group,
#' label_lower_bound, label_upper_bound, feature_weights.
setinfo <- function(object, ...) UseMethod("setinfo")

#' @export
setinfo.xgb.DMatrix <- function(object, name, info, ...) {
  stopifnot(is.character(name), length(name) == 1L)
  .Call(XTBDMatrixSetInfo_R, object$handle, name, as.numeric(info))
  invisible(TRUE)
}

#' Read a meta-info field back (label, weight, base_margin, ...).
getinfo <- function(object, ...) UseMethod("getinfo")

#' @export
getinfo.xgb.DMatrix <- function(object, name, ...) {
  .Call(XTBDMatrixGetInfo_R, object$handle, name)
}

#' Take a row subset as a new xgb.DMatrix (1-based row ids, like the
#' reference's xgb.slice.DMatrix).  Meta info (labels, weights, margins)
#' rides along; set allow_groups = TRUE when slicing a ranking matrix by
#' whole query groups.
xgb.slice.DMatrix <- function(dmat, idxset, allow_groups = FALSE) {
  stopifnot(inherits(dmat, "xgb.DMatrix"))
  handle <- .Call(XTBDMatrixSlice_R, dmat$handle,
                  as.integer(idxset) - 1L, as.integer(allow_groups))
  structure(list(handle = handle), class = "xgb.DMatrix")
}

xgb.DMatrix.num.row <- function(dmat) {
  .Call(XTBDMatrixNumRow_R, dmat$handle)
}

xgb.DMatrix.num.col <- function(dmat) {
  .Call(XTBDMatrixNumCol_R, dmat$handle)
}

#' @export
dim.xgb.DMatrix <- function(x) {
  c(xgb.DMatrix.num.row(x), xgb.DMatrix.num.col(x))
}
