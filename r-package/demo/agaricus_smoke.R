# Smoke: train/predict/save/load through the R binding
# (reference: R-package/demo/basic_walkthrough.R shape, synthetic
# agaricus-like binary data).
#
# Run:  (cd native && make capi)
#       PYTHONPATH=/root/repo Rscript r-package/demo/agaricus_smoke.R
# (after R CMD INSTALL r-package)
library(xgboost.tpu)

set.seed(1)
n <- 1000; f <- 8
x <- matrix(rnorm(n * f), n, f)
x[sample(length(x), n)] <- NA                 # missing values
y <- as.numeric(ifelse(is.na(x[, 1]), 0, x[, 1]) > 0)

dtrain <- xgb.DMatrix(x, label = y)
stopifnot(all(dim(dtrain) == c(n, f)))

bst <- xgb.train(list(objective = "binary:logistic", max_depth = 4,
                      eta = 0.3, eval_metric = "logloss"),
                 dtrain, nrounds = 10, evals = list(train = dtrain))

p <- predict(bst, dtrain)
err <- mean((p > 0.5) != y)
cat(sprintf("train error: %.4f\n", err))
stopifnot(err < 0.1)

f1 <- tempfile(fileext = ".json")
xgb.save(bst, f1)
bst2 <- xgb.load(f1)
stopifnot(max(abs(predict(bst2, dtrain) - p)) == 0)

raw <- xgb.save.raw(bst, "ubj")
bst3 <- xgb.load.raw(raw)
stopifnot(max(abs(predict(bst3, dtrain) - p)) == 0)

cat("R binding smoke: OK (", length(xgb.dump(bst)), "trees )\n")

# --- cross-validation (xgb.cv) ------------------------------------------
cv <- xgb.cv(list(objective = "binary:logistic", max_depth = 3,
                  eta = 0.3, eval_metric = "logloss"),
             dtrain, nrounds = 8, nfold = 3,
             early_stopping_rounds = 3, verbose = FALSE)
stopifnot(nrow(cv$evaluation_log) >= 1,
          "test-logloss_mean" %in% colnames(cv$evaluation_log))

# --- setinfo / getinfo ---------------------------------------------------
setinfo(dtrain, "weight", runif(n, 0.5, 2))
stopifnot(length(getinfo(dtrain, "weight")) == n)
stopifnot(all(abs(getinfo(dtrain, "label") - y) < 1e-7))

# --- weighted ranking with early stopping --------------------------------
gsize <- rep(20, n / 20)
drank <- xgb.DMatrix(x, label = sample(0:4, n, TRUE), group = gsize)
brk <- xgb.train(list(objective = "rank:ndcg", eval_metric = "ndcg@5",
                      max_depth = 3), drank, nrounds = 8,
                 evals = list(train = drank),
                 early_stopping_rounds = 3, verbose = FALSE)
stopifnot(!is.null(brk$evaluation_log))

# --- importance ----------------------------------------------------------
imp <- xgb.importance(bst)
stopifnot(nrow(imp) >= 1, abs(sum(imp$Gain) - 1) < 1e-6)

cat("R deep-surface smoke OK\n")
