/* R .Call glue over the xgboost_tpu C ABI (libxtb_capi.so).
 *
 * Role of the reference's R-package/src/xgboost_R.cc, written fresh for
 * this ABI: every entry converts R objects (column-major double matrices,
 * numeric vectors, character scalars) to the row-major float buffers the
 * XGB* C functions take, wraps handles in R external pointers with
 * finalizers, and turns non-zero return codes into R errors carrying
 * XGBGetLastError().
 *
 * Build: R CMD INSTALL links this against libxtb_capi.so (see Makevars);
 * the identical call SEQUENCE is exercised C-side by
 * native/r_glue_seq.c (tests/test_c_api.py::test_r_glue_sequence) so the
 * ABI contract stays pinned even on machines without R.
 */
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#include <R.h>
#include <Rinternals.h>

typedef void* DMatrixHandle;
typedef void* BoosterHandle;
typedef uint64_t bst_ulong;

extern const char* XGBGetLastError(void);
extern int XGDMatrixCreateFromMat(const float*, bst_ulong, bst_ulong, float,
                                  DMatrixHandle*);
extern int XGDMatrixSetFloatInfo(DMatrixHandle, const char*, const float*,
                                 bst_ulong);
extern int XGDMatrixSetUIntInfo(DMatrixHandle, const char*,
                                const unsigned*, bst_ulong);
extern int XGDMatrixNumRow(DMatrixHandle, bst_ulong*);
extern int XGDMatrixNumCol(DMatrixHandle, bst_ulong*);
extern int XGDMatrixFree(DMatrixHandle);
extern int XGBoosterCreate(const DMatrixHandle[], bst_ulong, BoosterHandle*);
extern int XGBoosterFree(BoosterHandle);
extern int XGBoosterSetParam(BoosterHandle, const char*, const char*);
extern int XGBoosterUpdateOneIter(BoosterHandle, int, DMatrixHandle);
extern int XGBoosterEvalOneIter(BoosterHandle, int, DMatrixHandle[],
                                const char*[], bst_ulong, const char**);
extern int XGBoosterPredict(BoosterHandle, DMatrixHandle, int, unsigned, int,
                            bst_ulong*, const float**);
extern int XGBoosterSaveModel(BoosterHandle, const char*);
extern int XGBoosterLoadModel(BoosterHandle, const char*);
extern int XGBoosterSaveModelToBuffer(BoosterHandle, const char*, bst_ulong*,
                                      const char**);
extern int XGBoosterLoadModelFromBuffer(BoosterHandle, const void*,
                                        bst_ulong);
extern int XGBoosterDumpModelEx(BoosterHandle, const char*, int, const char*,
                                bst_ulong*, const char***);
extern int XGDMatrixGetFloatInfo(const DMatrixHandle, const char*,
                                 bst_ulong*, const float**);
extern int XGDMatrixSliceDMatrixEx(DMatrixHandle, const int*, bst_ulong,
                                   DMatrixHandle*, int);
extern int XGBoosterSetAttr(BoosterHandle, const char*, const char*);
extern int XGBoosterGetAttr(BoosterHandle, const char*, const char**, int*);

#define XTB_CHECK(call)                                                    \
  do {                                                                     \
    if ((call) != 0) Rf_error("xgboost.tpu: %s", XGBGetLastError());       \
  } while (0)

/* ---------------------------------------------------------- handles --- */

static void dmatrix_finalizer(SEXP ext) {
  DMatrixHandle h = R_ExternalPtrAddr(ext);
  if (h != NULL) {
    XGDMatrixFree(h);
    R_ClearExternalPtr(ext);
  }
}

static void booster_finalizer(SEXP ext) {
  BoosterHandle h = R_ExternalPtrAddr(ext);
  if (h != NULL) {
    XGBoosterFree(h);
    R_ClearExternalPtr(ext);
  }
}

static SEXP wrap_handle(void* h, R_CFinalizer_t fin) {
  SEXP ext = PROTECT(R_MakeExternalPtr(h, R_NilValue, R_NilValue));
  R_RegisterCFinalizerEx(ext, fin, TRUE);
  UNPROTECT(1);
  return ext;
}

/* ---------------------------------------------------------- DMatrix --- */

SEXP XTBDMatrixCreateFromMat_R(SEXP mat, SEXP missing) {
  int nrow = Rf_nrows(mat), ncol = Rf_ncols(mat);
  double* src = REAL(mat);
  float* buf = (float*)malloc((size_t)nrow * ncol * sizeof(float));
  if (buf == NULL) Rf_error("xgboost.tpu: out of memory");
  /* R matrices are column-major; the ABI takes row-major */
  for (int j = 0; j < ncol; ++j)
    for (int i = 0; i < nrow; ++i)
      buf[(size_t)i * ncol + j] = (float)src[(size_t)j * nrow + i];
  DMatrixHandle h = NULL;
  int rc = XGDMatrixCreateFromMat(buf, (bst_ulong)nrow, (bst_ulong)ncol,
                                  (float)Rf_asReal(missing), &h);
  free(buf);
  if (rc != 0) Rf_error("xgboost.tpu: %s", XGBGetLastError());
  return wrap_handle(h, dmatrix_finalizer);
}

SEXP XTBDMatrixSetInfo_R(SEXP handle, SEXP name, SEXP vec) {
  DMatrixHandle h = R_ExternalPtrAddr(handle);
  const char* field = CHAR(Rf_asChar(name));
  R_xlen_t n = Rf_xlength(vec);
  if (strcmp(field, "group") == 0) {
    unsigned* buf = (unsigned*)malloc(n * sizeof(unsigned));
    if (buf == NULL) Rf_error("xgboost.tpu: out of memory");
    for (R_xlen_t i = 0; i < n; ++i) buf[i] = (unsigned)REAL(vec)[i];
    int rc = XGDMatrixSetUIntInfo(h, field, buf, (bst_ulong)n);
    free(buf);
    XTB_CHECK(rc);
  } else {
    float* buf = (float*)malloc(n * sizeof(float));
    if (buf == NULL) Rf_error("xgboost.tpu: out of memory");
    for (R_xlen_t i = 0; i < n; ++i) buf[i] = (float)REAL(vec)[i];
    int rc = XGDMatrixSetFloatInfo(h, field, buf, (bst_ulong)n);
    free(buf);
    XTB_CHECK(rc);
  }
  return R_NilValue;
}

SEXP XTBDMatrixNumRow_R(SEXP handle) {
  bst_ulong n = 0;
  XTB_CHECK(XGDMatrixNumRow(R_ExternalPtrAddr(handle), &n));
  return Rf_ScalarInteger((int)n);
}

SEXP XTBDMatrixNumCol_R(SEXP handle) {
  bst_ulong n = 0;
  XTB_CHECK(XGDMatrixNumCol(R_ExternalPtrAddr(handle), &n));
  return Rf_ScalarInteger((int)n);
}

/* ---------------------------------------------------------- Booster --- */

SEXP XTBBoosterCreate_R(SEXP dmats) {
  R_xlen_t n = Rf_xlength(dmats);
  DMatrixHandle* arr =
      (DMatrixHandle*)malloc((n ? n : 1) * sizeof(DMatrixHandle));
  if (arr == NULL) Rf_error("xgboost.tpu: out of memory");
  for (R_xlen_t i = 0; i < n; ++i)
    arr[i] = R_ExternalPtrAddr(VECTOR_ELT(dmats, i));
  BoosterHandle h = NULL;
  int rc = XGBoosterCreate(arr, (bst_ulong)n, &h);
  free(arr);
  if (rc != 0) Rf_error("xgboost.tpu: %s", XGBGetLastError());
  return wrap_handle(h, booster_finalizer);
}

SEXP XTBBoosterSetParam_R(SEXP handle, SEXP name, SEXP val) {
  XTB_CHECK(XGBoosterSetParam(R_ExternalPtrAddr(handle),
                              CHAR(Rf_asChar(name)), CHAR(Rf_asChar(val))));
  return R_NilValue;
}

SEXP XTBBoosterUpdateOneIter_R(SEXP handle, SEXP iter, SEXP dtrain) {
  XTB_CHECK(XGBoosterUpdateOneIter(R_ExternalPtrAddr(handle),
                                   Rf_asInteger(iter),
                                   R_ExternalPtrAddr(dtrain)));
  return R_NilValue;
}

SEXP XTBBoosterEvalOneIter_R(SEXP handle, SEXP iter, SEXP dmats,
                             SEXP names) {
  R_xlen_t n = Rf_xlength(dmats);
  if (TYPEOF(names) != STRSXP || Rf_xlength(names) != n)
    Rf_error("xgboost.tpu: eval names must be a character vector matching "
             "the eval list");
  DMatrixHandle* arr =
      (DMatrixHandle*)malloc((n ? n : 1) * sizeof(DMatrixHandle));
  const char** nm = (const char**)malloc((n ? n : 1) * sizeof(char*));
  if (arr == NULL || nm == NULL) {
    free(arr);
    free(nm);
    Rf_error("xgboost.tpu: out of memory");
  }
  for (R_xlen_t i = 0; i < n; ++i) {
    arr[i] = R_ExternalPtrAddr(VECTOR_ELT(dmats, i));
    nm[i] = CHAR(STRING_ELT(names, i));
  }
  const char* out = NULL;
  int rc = XGBoosterEvalOneIter(R_ExternalPtrAddr(handle),
                                Rf_asInteger(iter), arr, nm, (bst_ulong)n,
                                &out);
  free(arr);
  free(nm);
  if (rc != 0) Rf_error("xgboost.tpu: %s", XGBGetLastError());
  return Rf_mkString(out ? out : "");
}

SEXP XTBBoosterPredict_R(SEXP handle, SEXP dmat, SEXP option_mask,
                         SEXP ntree_limit, SEXP training) {
  bst_ulong len = 0;
  const float* res = NULL;
  XTB_CHECK(XGBoosterPredict(R_ExternalPtrAddr(handle),
                             R_ExternalPtrAddr(dmat),
                             Rf_asInteger(option_mask),
                             (unsigned)Rf_asInteger(ntree_limit),
                             Rf_asInteger(training), &len, &res));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, (R_xlen_t)len));
  for (bst_ulong i = 0; i < len; ++i) REAL(out)[i] = (double)res[i];
  UNPROTECT(1);
  return out;
}

SEXP XTBBoosterSaveModel_R(SEXP handle, SEXP fname) {
  XTB_CHECK(XGBoosterSaveModel(R_ExternalPtrAddr(handle),
                               CHAR(Rf_asChar(fname))));
  return R_NilValue;
}

SEXP XTBBoosterLoadModel_R(SEXP handle, SEXP fname) {
  XTB_CHECK(XGBoosterLoadModel(R_ExternalPtrAddr(handle),
                               CHAR(Rf_asChar(fname))));
  return R_NilValue;
}

SEXP XTBBoosterSaveModelToRaw_R(SEXP handle, SEXP format) {
  bst_ulong len = 0;
  const char* buf = NULL;
  XTB_CHECK(XGBoosterSaveModelToBuffer(R_ExternalPtrAddr(handle),
                                       CHAR(Rf_asChar(format)), &len, &buf));
  SEXP out = PROTECT(Rf_allocVector(RAWSXP, (R_xlen_t)len));
  memcpy(RAW(out), buf, len);
  UNPROTECT(1);
  return out;
}

SEXP XTBBoosterLoadModelFromRaw_R(SEXP handle, SEXP raw) {
  XTB_CHECK(XGBoosterLoadModelFromBuffer(R_ExternalPtrAddr(handle),
                                         RAW(raw),
                                         (bst_ulong)Rf_xlength(raw)));
  return R_NilValue;
}

SEXP XTBBoosterDumpModel_R(SEXP handle, SEXP fmap, SEXP with_stats,
                           SEXP format) {
  bst_ulong len = 0;
  const char** dump = NULL;
  XTB_CHECK(XGBoosterDumpModelEx(R_ExternalPtrAddr(handle),
                                 CHAR(Rf_asChar(fmap)),
                                 Rf_asInteger(with_stats),
                                 CHAR(Rf_asChar(format)), &len, &dump));
  SEXP out = PROTECT(Rf_allocVector(STRSXP, (R_xlen_t)len));
  for (bst_ulong i = 0; i < len; ++i)
    SET_STRING_ELT(out, (R_xlen_t)i, Rf_mkChar(dump[i]));
  UNPROTECT(1);
  return out;
}

SEXP XTBDMatrixGetInfo_R(SEXP handle, SEXP name) {
  bst_ulong len = 0;
  const float* ptr = NULL;
  XTB_CHECK(XGDMatrixGetFloatInfo(R_ExternalPtrAddr(handle),
                                  CHAR(Rf_asChar(name)), &len, &ptr));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, (R_xlen_t)len));
  for (bst_ulong i = 0; i < len; ++i) REAL(out)[i] = (double)ptr[i];
  UNPROTECT(1);
  return out;
}

SEXP XTBDMatrixSlice_R(SEXP handle, SEXP idx, SEXP allow_groups) {
  /* idx: 0-based integer row ids (xgb.slice.DMatrix converts from R's
     1-based).  allow_groups mirrors the reference's slice flag (needed when
     slicing a ranking DMatrix by whole groups). */
  int n = Rf_length(idx);
  DMatrixHandle out = NULL;
  XTB_CHECK(XGDMatrixSliceDMatrixEx(R_ExternalPtrAddr(handle), INTEGER(idx),
                                    (bst_ulong)n, &out,
                                    Rf_asInteger(allow_groups)));
  return wrap_handle(out, dmatrix_finalizer);
}

SEXP XTBBoosterSetAttr_R(SEXP handle, SEXP key, SEXP val) {
  XTB_CHECK(XGBoosterSetAttr(R_ExternalPtrAddr(handle),
                             CHAR(Rf_asChar(key)),
                             val == R_NilValue ? NULL
                                               : CHAR(Rf_asChar(val))));
  return R_NilValue;
}

SEXP XTBBoosterGetAttr_R(SEXP handle, SEXP key) {
  const char* out = NULL;
  int ok = 0;
  XTB_CHECK(XGBoosterGetAttr(R_ExternalPtrAddr(handle),
                             CHAR(Rf_asChar(key)), &out, &ok));
  if (!ok) return R_NilValue;
  return Rf_mkString(out);
}

/* ----------------------------------------------------- registration --- */

static const R_CallMethodDef CallEntries[] = {
    {"XTBDMatrixCreateFromMat_R", (DL_FUNC)&XTBDMatrixCreateFromMat_R, 2},
    {"XTBDMatrixSetInfo_R", (DL_FUNC)&XTBDMatrixSetInfo_R, 3},
    {"XTBDMatrixNumRow_R", (DL_FUNC)&XTBDMatrixNumRow_R, 1},
    {"XTBDMatrixNumCol_R", (DL_FUNC)&XTBDMatrixNumCol_R, 1},
    {"XTBBoosterCreate_R", (DL_FUNC)&XTBBoosterCreate_R, 1},
    {"XTBBoosterSetParam_R", (DL_FUNC)&XTBBoosterSetParam_R, 3},
    {"XTBBoosterUpdateOneIter_R", (DL_FUNC)&XTBBoosterUpdateOneIter_R, 3},
    {"XTBBoosterEvalOneIter_R", (DL_FUNC)&XTBBoosterEvalOneIter_R, 4},
    {"XTBBoosterPredict_R", (DL_FUNC)&XTBBoosterPredict_R, 5},
    {"XTBBoosterSaveModel_R", (DL_FUNC)&XTBBoosterSaveModel_R, 2},
    {"XTBBoosterLoadModel_R", (DL_FUNC)&XTBBoosterLoadModel_R, 2},
    {"XTBBoosterSaveModelToRaw_R", (DL_FUNC)&XTBBoosterSaveModelToRaw_R, 2},
    {"XTBBoosterLoadModelFromRaw_R", (DL_FUNC)&XTBBoosterLoadModelFromRaw_R,
     2},
    {"XTBBoosterDumpModel_R", (DL_FUNC)&XTBBoosterDumpModel_R, 4},
    {"XTBDMatrixGetInfo_R", (DL_FUNC)&XTBDMatrixGetInfo_R, 2},
    {"XTBDMatrixSlice_R", (DL_FUNC)&XTBDMatrixSlice_R, 3},
    {"XTBBoosterSetAttr_R", (DL_FUNC)&XTBBoosterSetAttr_R, 3},
    {"XTBBoosterGetAttr_R", (DL_FUNC)&XTBBoosterGetAttr_R, 2},
    {NULL, NULL, 0}};

void R_init_xgboost_tpu(DllInfo* dll) {
  R_registerRoutines(dll, NULL, CallEntries, NULL, NULL);
  R_useDynamicSymbols(dll, FALSE);
}
