"""Candidate validation gate: metric threshold vs the incumbent plus a
bitwise snapshot checksum.

A continuation-trained candidate may only reach the serving fleet through
this gate (docs/serving.md "Online model lifecycle").  Two halves:

1. **Metric gate.**  Candidate and incumbent are both scored on the SAME
   held-out eval window via ``Booster.eval_set`` (the exact metrics
   training uses, so gate numbers and training logs agree to the digit).
   The direction-normalized improvement (higher-is-better metrics flip
   sign) must be at least ``GateConfig.min_improvement``; 0.0 means "no
   worse than the incumbent", a negative value tolerates that much
   regression (the fresh-window-drift case), a positive one demands a
   real win.

2. **Bitwise checksum.**  On publish, the model store records a SHA-256
   over the candidate's snapshot arena fields
   (:func:`~xgboost_tpu.serving.modelstore.arena_checksum`); the manager
   re-derives it from the mmapped arena before activation.  A mismatch —
   torn publish, bit rot, nondeterministic export — is a deterministic
   reject: the candidate is never activated and the incumbent keeps
   serving.

Every reject path is **deterministic**: the same candidate, incumbent,
and eval window produce the same :class:`GateDecision` every time, and a
rejected cycle leaves zero serving-side state behind.  The
``lifecycle.validate`` fault seam fires at gate entry (docs/reliability.md):
``exception`` turns into a rejected cycle (reason ``fault``), ``kill``
proves a validator death cannot disturb the incumbent.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from ..callback import EarlyStopping
from ..reliability import faults as _faults

__all__ = ["GateConfig", "GateDecision", "score_on", "validate_candidate"]


@dataclasses.dataclass(frozen=True)
class GateConfig:
    """Gate knobs.

    ``metric``: which eval metric decides (None = the last metric the
    params configure, matching EarlyStopping's convention).
    ``min_improvement``: required direction-normalized improvement over
    the incumbent (see module docstring).  ``higher_is_better``: override
    the auc/map/ndcg/pre name inference.
    """

    metric: Optional[str] = None
    min_improvement: float = 0.0
    higher_is_better: Optional[bool] = None

    def maximize(self, metric: str) -> bool:
        if self.higher_is_better is not None:
            return self.higher_is_better
        return metric.startswith(EarlyStopping._MAXIMIZE_METRICS)


@dataclasses.dataclass
class GateDecision:
    """One gate verdict (deterministic for fixed inputs)."""

    accepted: bool
    # "accepted" | "metric" | "checksum" | "shadow" | "fault" | "resource"
    reason: str
    metric: str = ""
    candidate_score: float = float("nan")
    incumbent_score: float = float("nan")
    improvement: float = float("nan")
    detail: str = ""


def score_on(booster, dval, metric: Optional[str] = None,
             ) -> Tuple[float, str, Dict[str, float]]:
    """Score ``booster`` on ``dval`` with its configured eval metrics.
    Returns (score, metric_name, all_scores); ``metric=None`` picks the
    last configured metric (EarlyStopping's convention)."""
    msg = booster.eval_set([(dval, "gate")], iteration=0)
    scores: Dict[str, float] = {}
    for part in msg.strip().split("\t")[1:]:
        key, val = part.rsplit(":", 1)
        scores[key.split("-", 1)[1]] = float(val)
    if not scores:
        raise ValueError(f"eval_set produced no metrics: {msg!r}")
    if metric is None:
        metric = list(scores)[-1]
    if metric not in scores:
        raise ValueError(f"gate metric {metric!r} not among configured "
                         f"eval metrics {sorted(scores)}")
    return scores[metric], metric, scores


def validate_candidate(candidate, incumbent, dval,
                       config: Optional[GateConfig] = None) -> GateDecision:
    """The metric half of the gate: score both boosters on the eval
    window, compare direction-normalized.  Raises
    :class:`~xgboost_tpu.reliability.faults.FaultInjected` when the
    ``lifecycle.validate`` seam fires with an ``exception`` spec — the
    manager maps that onto the deterministic reject path."""
    config = config or GateConfig()
    _faults.maybe_inject("lifecycle.validate")
    cand, metric, _ = score_on(candidate, dval, config.metric)
    incu, _, _ = score_on(incumbent, dval, metric)
    improvement = (cand - incu) if config.maximize(metric) else (incu - cand)
    if improvement >= config.min_improvement:
        return GateDecision(True, "accepted", metric, cand, incu,
                            improvement)
    return GateDecision(
        False, "metric", metric, cand, incu, improvement,
        detail=(f"gate-{metric}: candidate {cand:.6g} vs incumbent "
                f"{incu:.6g} (improvement {improvement:.6g} < required "
                f"{config.min_improvement:.6g})"))
