"""xgboost_tpu.lifecycle — online model lifecycle over the serving fleet.

The train → validate → hot-swap loop (docs/serving.md "Online model
lifecycle"):

- :class:`LifecycleManager` — drives one model's continuation cycles
  against a :class:`~xgboost_tpu.serving.fleet.ServingFleet`:
  crash-safe continuation training on a fresh-traffic window, the
  validation gate, shadow scoring, the zero-drop hot-swap, rollback.
- :class:`LifecycleConfig` / :class:`CycleReport` — knobs and outcome.
- :class:`GateConfig` / :class:`GateDecision` /
  :func:`validate_candidate` — the metric + bitwise-checksum gate,
  usable standalone.
- :class:`FreshWindow` — bounded sliding buffer of labeled traffic.

Quick start::

    from xgboost_tpu.lifecycle import LifecycleManager, FreshWindow

    window = FreshWindow(max_rows=100_000)
    window.append(X_fresh, y_fresh)          # as labels arrive
    mgr = LifecycleManager(fleet, "ctr", rounds_per_cycle=5,
                           shadow_fraction=0.1)
    report = mgr.run_cycle(window)           # train -> gate -> swap
    if report.swapped and regret:
        mgr.rollback()
"""
from .gate import GateConfig, GateDecision, score_on, validate_candidate
from .manager import (CycleReport, LifecycleConfig, LifecycleManager,
                      ShadowRejected)
from .window import FreshWindow

__all__ = [
    "LifecycleManager",
    "LifecycleConfig",
    "CycleReport",
    "ShadowRejected",
    "GateConfig",
    "GateDecision",
    "validate_candidate",
    "score_on",
    "FreshWindow",
]
