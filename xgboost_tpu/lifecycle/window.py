"""Fresh-traffic window: the rows a continuation cycle trains on.

Production continual learning trains each cycle on a sliding window of
recent traffic (labels arrive after serving).  :class:`FreshWindow` is
that buffer: append scored batches as their labels land, and the
lifecycle manager turns the window into a DMatrix per cycle.  The window
is bounded — beyond ``max_rows`` the OLDEST rows fall off, so a
long-running loop holds a fixed-size recency window, not an ever-growing
dataset.

For windows too large to keep resident, ``to_dmatrix`` can route through
the external-memory path (``extmem_chunk_rows``): the window streams into
an :class:`~xgboost_tpu.data.extmem.ExtMemQuantileDMatrix` in chunks, the
"Out-of-Core GPU Gradient Boosting" (arXiv:2005.09148) page machinery
applied to the continuation window.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

__all__ = ["FreshWindow"]


class FreshWindow:
    """Bounded sliding buffer of (rows, labels[, weights]) batches."""

    def __init__(self, max_rows: Optional[int] = None) -> None:
        self.max_rows = int(max_rows) if max_rows else None
        self._X: List[np.ndarray] = []
        self._y: List[np.ndarray] = []
        self._w: List[Optional[np.ndarray]] = []

    def append(self, X, y, weight=None) -> None:
        X = np.atleast_2d(np.asarray(X, np.float32))
        y = np.asarray(y, np.float32).reshape(-1)
        if len(X) != len(y):
            raise ValueError(f"rows ({len(X)}) != labels ({len(y)})")
        if weight is not None:
            weight = np.asarray(weight, np.float32).reshape(-1)
            if len(weight) != len(y):
                raise ValueError("weight length != label length")
        if self._w and (weight is None) != (self._w[-1] is None):
            raise ValueError("either every batch carries weights or none")
        self._X.append(X)
        self._y.append(y)
        self._w.append(weight)
        self._trim()

    def _trim(self) -> None:
        if self.max_rows is None:
            return
        while len(self) > self.max_rows and self._X:
            over = len(self) - self.max_rows
            if len(self._X[0]) <= over:  # whole oldest batch falls off
                self._X.pop(0), self._y.pop(0), self._w.pop(0)
            else:
                self._X[0] = self._X[0][over:]
                self._y[0] = self._y[0][over:]
                if self._w[0] is not None:
                    self._w[0] = self._w[0][over:]

    def __len__(self) -> int:
        return int(sum(len(y) for y in self._y))

    def clear(self) -> None:
        self._X, self._y, self._w = [], [], []

    def arrays(self):
        """(X, y, weight-or-None) as single concatenated arrays."""
        if not self._X:
            raise ValueError("FreshWindow is empty")
        X = np.concatenate(self._X, axis=0)
        y = np.concatenate(self._y)
        w = (np.concatenate([w for w in self._w])
             if self._w and self._w[0] is not None else None)
        return X, y, w

    def to_dmatrix(self, extmem_chunk_rows: Optional[int] = None,
                   max_bin: int = 256, **kw):
        """Materialize the window.  Default: an in-memory DMatrix.  With
        ``extmem_chunk_rows``, stream through ExtMemQuantileDMatrix pages
        instead (quantised, spillable — the large-window path)."""
        X, y, w = self.arrays()
        if extmem_chunk_rows:
            from ..data.extmem import DataIter, ExtMemQuantileDMatrix

            chunk = int(extmem_chunk_rows)

            class _WindowIter(DataIter):
                def __init__(self) -> None:
                    super().__init__()
                    self._i = 0

                def next(self, input_data) -> bool:
                    lo = self._i * chunk
                    if lo >= len(X):
                        return False
                    hi = min(lo + chunk, len(X))
                    batch = {"data": X[lo:hi], "label": y[lo:hi]}
                    if w is not None:
                        batch["weight"] = w[lo:hi]
                    input_data(**batch)
                    self._i += 1
                    return True

                def reset(self) -> None:
                    self._i = 0

            return ExtMemQuantileDMatrix(_WindowIter(), max_bin=max_bin,
                                         **kw)
        from ..data.dmatrix import DMatrix

        return DMatrix(X, label=y, weight=w, **kw)
