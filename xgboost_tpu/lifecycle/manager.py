"""LifecycleManager: the train → validate → hot-swap loop over a fleet.

The continual-learning loop production boosting systems run
(docs/serving.md "Online model lifecycle"), closed over this repo's
pieces: additive-ensemble continuation (``train(xgb_model=)``, the Chen &
Guestrin additive semantics applied online), the crash-safe checkpoint
machinery, the mmap model store, and the fleet's serialized control
channel.  One :meth:`~LifecycleManager.run_cycle` is one state-machine
pass::

    IDLE -> TRAIN -> VALIDATE -> PUBLISH(+checksum) -> [SHADOW] -> SWAP -> IDLE
                \\______________________________________________________/
                          any reject/fault: incumbent untouched

Guarantees (pinned by ``tests/test_lifecycle.py`` +
``scripts/lifecycle_smoke.py``):

- **Crash-safe continuation**: each cycle trains under a
  CheckpointCallback in a per-incumbent directory; a cycle killed
  mid-training resumes from its newest checkpoint on the next call
  (``resume_from`` > ``xgb_model`` precedence in ``train()``) and lands on
  the same final round.
- **Deterministic reject**: a gate failure (metric, checksum, or a
  ``lifecycle.validate`` fault) leaves the incumbent serving
  bit-identically, every time, with nothing activated.
- **Kill-mid-swap safety**: the ``lifecycle.swap`` seam fires BEFORE the
  store's ``set_active`` commit, so a process killed there leaves a store
  whose restarted fleet serves the incumbent.
- **Zero dropped requests**: the swap itself is fleet control frames on
  each replica's serialized connection — predicts in flight complete on
  whichever version was active when they were dispatched, and the old
  version is retired only after its replica's traffic drained past the
  retire frame.
- **Rollback**: the previous version stays published, resident, and
  loadable; :meth:`rollback` repoints the fleet (and the durable
  manifest) back at it.
"""
from __future__ import annotations

import contextlib
import dataclasses
import os
import shutil
import time
from typing import Any, Dict, List, Optional

from ..reliability import faults as _faults
from ..telemetry import flight as _flight
from .gate import GateConfig, GateDecision, validate_candidate
from .window import FreshWindow

__all__ = ["LifecycleConfig", "LifecycleManager", "CycleReport",
           "ShadowRejected"]


class ShadowRejected(RuntimeError):
    """A shadow-phase distribution gate (KS, PSI, or per-decile
    calibration — ``LifecycleConfig.shadow_max_ks`` /
    ``shadow_max_psi`` / ``shadow_max_calibration``) refused the
    candidate; carries the comparator stats."""

    def __init__(self, message: str, stats: Optional[dict] = None) -> None:
        super().__init__(message)
        self.stats = dict(stats or {})

_instruments = None


def instruments():
    """(phase hist, swaps, rollbacks, rejected) xtb_lifecycle_* families."""
    global _instruments
    if _instruments is None:
        from ..telemetry.registry import get_registry

        reg = get_registry()
        _instruments = (
            reg.histogram("xtb_lifecycle_phase_seconds",
                          "wall-clock per lifecycle phase", ("phase",)),
            reg.counter("xtb_lifecycle_swaps_total",
                        "candidates hot-swapped into serving"),
            reg.counter("xtb_lifecycle_rollbacks_total",
                        "serving versions rolled back"),
            reg.counter("xtb_lifecycle_rejected_total",
                        "candidates rejected by the gate, by reason",
                        ("reason",)),
        )
    return _instruments


@dataclasses.dataclass
class LifecycleConfig:
    """Cycle knobs.

    ``rounds_per_cycle``: continuation rounds K per cycle.
    ``checkpoint_dir``: root for crash-safe mid-continuation checkpoints
    (one subdirectory per incumbent version; None disables — a killed
    cycle then restarts its training leg from the incumbent).
    ``shadow_fraction`` / ``shadow_min_pairs`` / ``shadow_timeout_s``:
    pre-swap shadow phase — mirror that fraction of live traffic onto the
    candidate until that many comparator pairs (or the timeout) before
    activating; 0.0 skips the phase.
    ``shadow_max_ks``: distribution gate on the shadow phase — reject the
    candidate (reason ``shadow``) when the worst observed two-sample KS
    statistic between candidate and incumbent predictions exceeds this
    (the mean-abs divergence misses rank-reshuffling drift; KS catches
    it).  None disables the check.
    ``shadow_max_psi`` / ``shadow_max_calibration``: the other two
    comparator lenses — worst observed population-stability index
    (broad distribution shift KS's single-gap statistic understates) and
    worst per-incumbent-decile calibration gap (a candidate re-scoring
    one decile while matching on average).  None disables each.
    ``retire_keep``: versions kept resident behind the active one
    (>= 1 so rollback is instant).
    """

    rounds_per_cycle: int = 5
    checkpoint_dir: Optional[str] = None
    checkpoint_interval: int = 1
    gate: GateConfig = dataclasses.field(default_factory=GateConfig)
    shadow_fraction: float = 0.0
    shadow_min_pairs: int = 1
    shadow_timeout_s: float = 30.0
    shadow_max_ks: Optional[float] = None
    shadow_max_psi: Optional[float] = None
    shadow_max_calibration: Optional[float] = None
    retire_keep: int = 1

    def __post_init__(self) -> None:
        if self.rounds_per_cycle < 1:
            raise ValueError("rounds_per_cycle must be >= 1")
        if self.retire_keep < 1:
            raise ValueError("retire_keep must be >= 1 (rollback needs "
                             "the previous version resident)")


@dataclasses.dataclass
class CycleReport:
    """What one run_cycle did."""

    model: str
    incumbent_version: int
    candidate_version: Optional[int]    # None when never published
    swapped: bool
    decision: Optional[GateDecision]
    shadow: Optional[dict] = None       # comparator stats, when shadowed
    timings: Dict[str, float] = dataclasses.field(default_factory=dict)
    load_acks: Optional[List[dict]] = None
    # the cycle's trace id: stamped on every fleet control frame this
    # cycle broadcast and on its flight-ring events, so a CycleReport can
    # be joined against the merged trace/flight record of what actually
    # happened on the replicas
    trace_id: Optional[str] = None

    @property
    def accepted(self) -> bool:
        return self.swapped


class LifecycleManager:
    """Drive continuation cycles for one model name against a fleet.

    ``fleet`` needs the control surface of
    :class:`~xgboost_tpu.serving.fleet.ServingFleet` (``store_dir``,
    ``load_version``/``activate_version``/``retire_version``,
    ``set_shadow``/``clear_shadow``/``shadow_stats``); ``params`` defaults
    to the serving model's own archived training params.
    """

    def __init__(self, fleet, model: str,
                 params: Optional[Dict[str, Any]] = None,
                 config: Optional[LifecycleConfig] = None,
                 **overrides) -> None:
        from ..serving.modelstore import ModelStore

        if config is None:
            config = LifecycleConfig(**overrides)
        elif overrides:
            config = dataclasses.replace(config, **overrides)
        self.fleet = fleet
        self.model = model
        self.config = config
        if fleet.store_dir is None:
            raise ValueError("fleet has no model store (start() not run?)")
        self.store = ModelStore(fleet.store_dir)
        if self.store.active_version(model) is None:
            raise KeyError(f"model {model!r} is not in the fleet store")
        self._params = dict(params) if params is not None else None
        self._previous: Optional[int] = None  # rollback target
        # versions this manager loaded onto replicas (retire bookkeeping)
        self._resident = {self.serving_version()}
        self._cycles = 0
        self._cycle_trace: Optional[str] = None

    # ------------------------------------------------------------ accessors
    def serving_version(self) -> int:
        v = self.store.active_version(self.model)
        assert v is not None  # checked at construction
        return int(v)

    def params(self) -> Dict[str, Any]:
        if self._params is not None:
            return dict(self._params)
        bst = self.store.booster(self.model, self.serving_version())
        return dict(bst.params)

    @contextlib.contextmanager
    def _phase(self, name: str, timings: Dict[str, float]):
        from ..reliability import watchdog

        t0 = time.perf_counter()
        # watchdog bracket (warn -> all-thread stack dump; no stall
        # action: the phase runs on THIS thread, so there is no peer to
        # declare dead — the dump is the diagnosis, and the cycle's own
        # exception/gate machinery owns the recovery)
        with watchdog.guard("lifecycle.phase", phase=name):
            try:
                yield
            finally:
                dt = time.perf_counter() - t0
                timings[name] = dt
                instruments()[0].labels(name).observe(dt)
                _flight.record("event", f"lifecycle.{name}", seconds=dt,
                               trace=self._cycle_trace)
        watchdog.progress("lifecycle.phase", phase=name)

    def _ckpt_dir(self, incumbent_version: int) -> Optional[str]:
        if self.config.checkpoint_dir is None:
            return None
        # per-incumbent directory: a killed cycle resumes ITS checkpoints,
        # while the next cycle (new incumbent) starts clean
        return os.path.join(self.config.checkpoint_dir,
                            f"{self.model}_from_v{incumbent_version}")

    # ---------------------------------------------------------------- train
    def continue_training(self, window, *, num_rounds: Optional[int] = None,
                          evals=None, _base=None) -> "Any":
        """K more boosting rounds on the fresh window, continuing from the
        EXACT bytes being served (store-archived model).  Crash-safe: under
        a checkpoint_dir, a killed continuation resumes from its newest
        checkpoint (``resume_from`` wins over ``xgb_model`` — the round
        target is then TOTAL, so the resumed run lands on the same final
        round as an uninterrupted one).  Checkpoints are consumed on
        successful return: they exist to survive a crash DURING this
        continuation, and a later cycle resuming a finished one would
        re-propose the same candidate without ever seeing its window."""
        from ..reliability.checkpoint import (CheckpointCallback,
                                              latest_checkpoint)
        from ..training import train

        incumbent_v = self.serving_version()
        base = (_base if _base is not None
                else self.store.booster(self.model, incumbent_v))
        K = int(num_rounds or self.config.rounds_per_cycle)
        params = (dict(self._params) if self._params is not None
                  else dict(base.params))
        dwin = _as_dmatrix(window)
        ckpt_dir = self._ckpt_dir(incumbent_v)
        callbacks = []
        kw: Dict[str, Any] = {}
        total = base.num_boosted_rounds() + K
        if ckpt_dir is not None:
            callbacks.append(CheckpointCallback(
                ckpt_dir, interval=self.config.checkpoint_interval))
            if latest_checkpoint(ckpt_dir) is not None:
                # mid-continuation crash: resume_from takes precedence over
                # xgb_model and counts num_boost_round as the TOTAL target
                kw["resume_from"] = ckpt_dir
        if "resume_from" in kw:
            out = train(params, dwin, total, xgb_model=base, evals=evals,
                        callbacks=callbacks, verbose_eval=False, **kw)
        else:
            # fresh continuation: additive semantics — K more rounds on top
            out = train(params, dwin, K, xgb_model=base, evals=evals,
                        callbacks=callbacks, verbose_eval=False)
        if ckpt_dir is not None:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
        return out

    # ----------------------------------------------------------------- swap
    def swap(self, version: int, *, timings: Optional[dict] = None,
             trace: Optional[str] = None) -> Optional[dict]:
        """Hot-swap a PUBLISHED version into the fleet: double-buffered
        load, optional shadow phase (with the KS distribution gate when
        ``shadow_max_ks`` is set), durable activate, drain-ordered
        retire of versions beyond ``retire_keep``.  Returns the shadow
        comparator stats (None when the phase was skipped).  The
        ``lifecycle.swap`` seam fires before the durable commit — a kill
        there leaves the store (and any restarted fleet) on the
        incumbent."""
        cfg = self.config
        timings = timings if timings is not None else {}
        if trace is None:
            # direct swap() call (not via run_cycle): mint a FRESH id —
            # falling back to the previous cycle's would join this swap's
            # control frames to a cycle that already completed
            self._cycles += 1
            trace = (f"swap-{self.model}-v{int(version)}-"
                     f"{os.getpid():x}-{self._cycles}")
        self._cycle_trace = trace
        # the incumbent is what the FLEET is serving (its dispatcher view,
        # seeded from the committed manifest) — never the store's
        # latest-version fallback, which a publish just moved
        incumbent = self.fleet.active_version(self.model)
        if incumbent is None:
            incumbent = self.serving_version()
        version = int(version)
        with self._phase("load", timings):
            acks = self.fleet.load_version(self.model, version,
                                           trace=trace)
        self._resident.add(version)
        shadow_stats = None
        if cfg.shadow_fraction > 0.0:
            with self._phase("shadow", timings):
                shadow_stats = self._shadow_phase(version)
            # distribution gates, one per comparator lens: the candidate
            # redistributes scores beyond tolerance — drop it and leave
            # the incumbent serving (deterministic for a fixed traffic
            # replay).  KS = worst single ECDF gap, PSI = integrated
            # shift, calibration = worst per-decile re-scoring.
            for stat, limit, what in (
                    ("max_ks", cfg.shadow_max_ks, "KS"),
                    ("max_psi", cfg.shadow_max_psi, "PSI"),
                    ("max_cal", cfg.shadow_max_calibration,
                     "calibration")):
                val = (shadow_stats or {}).get(stat)
                if limit is None or val is None or val <= limit:
                    continue
                with contextlib.suppress(Exception):
                    self.fleet.retire_version(self.model, version,
                                              trace=trace)
                self._resident.discard(version)
                raise ShadowRejected(
                    f"shadow {what} gate: {stat} {val:.6g} > allowed "
                    f"{limit:.6g} over "
                    f"{(shadow_stats or {}).get('pairs', 0)} pairs",
                    shadow_stats)
        try:
            # kill here = dead BEFORE the durable commit: the manifest
            # still says incumbent, a fleet restart serves incumbent
            _faults.maybe_inject("lifecycle.swap")
            with self._phase("activate", timings):
                self.fleet.activate_version(self.model, version,
                                            trace=trace)
        except _faults.FaultInjected:
            # deterministic abort: drop the loaded-but-never-activated
            # candidate from the replicas; the incumbent never moved
            with contextlib.suppress(Exception):
                self.fleet.retire_version(self.model, version, trace=trace)
            self._resident.discard(version)
            raise
        self._previous = incumbent
        instruments()[1].inc()
        # retire everything beyond the rollback window (the retire_keep
        # newest non-active versions stay resident); the retire frame
        # drains behind each replica's in-flight traffic by design
        behind = sorted(self._resident - {version}, reverse=True)
        for old in behind[cfg.retire_keep:]:
            with contextlib.suppress(Exception):
                self.fleet.retire_version(self.model, old)
            self._resident.discard(old)
        return shadow_stats

    def _shadow_phase(self, version: int) -> dict:
        cfg = self.config
        self.fleet.set_shadow(self.model, version, cfg.shadow_fraction)
        try:
            deadline = time.monotonic() + cfg.shadow_timeout_s
            while time.monotonic() < deadline:
                st = self.fleet.shadow_stats(self.model)
                if st is not None and st["pairs"] >= cfg.shadow_min_pairs:
                    break
                time.sleep(0.02)
        finally:
            stats = self.fleet.clear_shadow(self.model)
        return stats or {"pairs": 0, "failures": 0, "mean_div": 0.0,
                         "max_div": 0.0}

    def rollback(self) -> int:
        """Repoint serving (fleet + durable manifest) at the previous
        version.  Returns the version now serving."""
        prev = self._previous
        if prev is None:
            raise RuntimeError("nothing to roll back to: no swap has "
                               "completed in this manager")
        current = self.serving_version()
        self.fleet.load_version(self.model, prev)  # no-op if resident
        self.fleet.activate_version(self.model, prev)
        self._resident.add(prev)
        self._previous = current
        instruments()[2].inc()
        return prev

    # ---------------------------------------------------------------- cycle
    def run_cycle(self, window, *, eval_window=None,
                  num_rounds: Optional[int] = None) -> CycleReport:
        """One full lifecycle pass; see the module docstring's state
        machine.  Never raises on a gate reject — the report says why."""
        cfg = self.config
        timings: Dict[str, float] = {}
        incumbent_v = self.serving_version()
        # the cycle trace id: on every control frame this cycle broadcasts,
        # on its flight events, and on the returned CycleReport — the join
        # key between "what the manager decided" and "what the fleet did"
        self._cycles += 1
        trace_id = (f"cycle-{self.model}-v{incumbent_v}-"
                    f"{os.getpid():x}-{self._cycles}")
        self._cycle_trace = trace_id
        _flight.record("event", "lifecycle.cycle_start", model=self.model,
                       incumbent=incumbent_v, trace=trace_id)
        # one deserialize per cycle: the same archived incumbent seeds the
        # continuation AND scores the gate's incumbent side
        incumbent = self.store.booster(self.model, incumbent_v)
        with self._phase("train", timings):
            candidate = self.continue_training(window, num_rounds=num_rounds,
                                               _base=incumbent)
        dval = _as_dmatrix(eval_window if eval_window is not None
                           else window)
        try:
            with self._phase("validate", timings):
                decision = validate_candidate(candidate, incumbent, dval,
                                              cfg.gate)
        except _faults.FaultInjected as e:
            instruments()[3].labels("fault").inc()
            return CycleReport(
                self.model, incumbent_v, None, False,
                GateDecision(False, "fault", detail=str(e)),
                timings=timings, trace_id=trace_id)
        if not decision.accepted:
            instruments()[3].labels("metric").inc()
            return CycleReport(self.model, incumbent_v, None, False,
                               decision, timings=timings,
                               trace_id=trace_id)
        try:
            with self._phase("publish", timings):
                version = self.store.publish(self.model, candidate)
                checksum_ok = self.store.verify_checksum(self.model,
                                                         version)
        except OSError as e:
            from ..reliability import resources as _resources

            if not _resources.is_resource_errno(e):
                # EACCES/EROFS/etc. is a misconfiguration BUG, not
                # pressure — masking it as a transient "resource" reject
                # would hide it forever (the checkpoint/journal ladders
                # make the same distinction)
                raise
            # resource exhaustion mid-publish (ENOSPC writing the arena,
            # EMFILE): the store cleaned its tmp files and the manifest
            # never moved — reject the cycle with reason "resource", the
            # incumbent untouched (docs/reliability.md "Resource
            # pressure & graceful degradation")
            instruments()[3].labels("resource").inc()
            return CycleReport(
                self.model, incumbent_v, None, False,
                GateDecision(False, "resource", decision.metric,
                             decision.candidate_score,
                             decision.incumbent_score,
                             decision.improvement, detail=str(e)),
                timings=timings, trace_id=trace_id)
        if not checksum_ok:
            # bitwise half of the gate: a torn/drifted arena must never
            # activate.  active still points at the incumbent, so the
            # published-but-rejected files are inert.
            instruments()[3].labels("checksum").inc()
            return CycleReport(
                self.model, incumbent_v, version, False,
                GateDecision(False, "checksum", decision.metric,
                             decision.candidate_score,
                             decision.incumbent_score,
                             decision.improvement,
                             detail="arena checksum mismatch after publish"),
                timings=timings, trace_id=trace_id)
        try:
            shadow_stats = self.swap(version, timings=timings,
                                     trace=trace_id)
        except ShadowRejected as e:
            # distribution half of the shadow phase: the candidate's
            # score distribution drifted past shadow_max_ks — rejected
            # with the incumbent untouched, like every other gate half
            instruments()[3].labels("shadow").inc()
            return CycleReport(
                self.model, incumbent_v, version, False,
                GateDecision(False, "shadow", decision.metric,
                             decision.candidate_score,
                             decision.incumbent_score,
                             decision.improvement, detail=str(e)),
                shadow=e.stats, timings=timings, trace_id=trace_id)
        except _faults.FaultInjected as e:
            instruments()[3].labels("fault").inc()
            return CycleReport(
                self.model, incumbent_v, version, False,
                GateDecision(False, "fault", decision.metric,
                             decision.candidate_score,
                             decision.incumbent_score,
                             decision.improvement, detail=str(e)),
                timings=timings, trace_id=trace_id)
        return CycleReport(self.model, incumbent_v, version, True, decision,
                           shadow=shadow_stats, timings=timings,
                           trace_id=trace_id)


def _as_dmatrix(window):
    """DMatrix | FreshWindow | (X, y[, weight]) -> DMatrix."""
    from ..data.dmatrix import DMatrix

    if isinstance(window, DMatrix):
        return window
    if isinstance(window, FreshWindow):
        return window.to_dmatrix()
    if isinstance(window, (tuple, list)):
        if len(window) == 2:
            X, y = window
            return DMatrix(X, label=y)
        if len(window) == 3:
            X, y, w = window
            return DMatrix(X, label=y, weight=w)
    raise TypeError(
        f"window must be a DMatrix, FreshWindow, or (X, y[, weight]) "
        f"tuple, got {type(window).__name__}")
