"""Training callbacks (reference: python-package/xgboost/callback.py).

Same contract as the reference: ``TrainingCallback`` subclasses get
before/after-iteration hooks with an ``evals_log`` history;
``CallbackContainer`` drives them from train()/cv() (callback.py:149).
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

_Score = Union[float, Tuple[float, float]]
_EvalsLog = Dict[str, Dict[str, List[_Score]]]


class TrainingCallback:
    """(reference: callback.py:51)"""

    def before_training(self, model):
        return model

    def after_training(self, model):
        return model

    def before_iteration(self, model, epoch: int, evals_log: _EvalsLog) -> bool:
        return False

    def after_iteration(self, model, epoch: int, evals_log: _EvalsLog) -> bool:
        """Return True to stop training."""
        return False

    # checkpoint/resume protocol (reliability/checkpoint.py): stateful
    # callbacks override both so an interrupted run resumes with the same
    # decisions (EarlyStopping patience, scheduler position, ...) as an
    # uninterrupted one.  State must be JSON-serializable.
    def state_dict(self) -> Optional[dict]:
        return None

    def load_state(self, state: dict) -> None:
        pass


class CallbackContainer:
    """Driver for a list of callbacks (reference: callback.py:149)."""

    def __init__(self, callbacks: Sequence[TrainingCallback], metric=None,
                 output_margin: bool = True, is_cv: bool = False):
        self.callbacks = list(callbacks)
        self.metric = metric
        self.history: _EvalsLog = collections.OrderedDict()
        self.is_cv = is_cv

    def before_training(self, model):
        for cb in self.callbacks:
            model = cb.before_training(model)
        return model

    def after_training(self, model):
        for cb in self.callbacks:
            model = cb.after_training(model)
        return model

    def before_iteration(self, model, epoch, dtrain, evals) -> bool:
        return any(cb.before_iteration(model, epoch, self.history) for cb in self.callbacks)

    def update_history(self, eval_str: str) -> None:
        # parse "[i]\tname-metric:v\t..." into history
        parts = eval_str.strip().split("\t")[1:]
        for p in parts:
            key, v = p.rsplit(":", 1)
            name, metric = key.split("-", 1)
            self.history.setdefault(name, collections.OrderedDict()).setdefault(
                metric, []
            ).append(float(v))

    def after_iteration(self, model, epoch, dtrain, evals) -> bool:
        if evals:
            from .telemetry import span

            with span("eval.eval_set"):
                msg = model.eval_set(evals, epoch, feval=self.metric)
            self.update_history(msg)
        return any(cb.after_iteration(model, epoch, self.history) for cb in self.callbacks)


class LearningRateScheduler(TrainingCallback):
    """(reference: callback.py:272)"""

    def __init__(self, learning_rates: Union[Callable[[int], float], Sequence[float]]):
        if callable(learning_rates):
            self.fn = learning_rates
        else:
            rates = list(learning_rates)
            self.fn = lambda epoch: rates[epoch]

    def after_iteration(self, model, epoch, evals_log) -> bool:
        return False

    def before_iteration(self, model, epoch, evals_log) -> bool:
        model.set_param("eta", self.fn(epoch))
        return False


class EarlyStopping(TrainingCallback):
    """(reference: callback.py:311) — stop when the watched metric stops improving."""

    def __init__(self, rounds: int, metric_name: Optional[str] = None,
                 data_name: Optional[str] = None, maximize: Optional[bool] = None,
                 save_best: bool = False, min_delta: float = 0.0):
        self.rounds = rounds
        self.metric_name = metric_name
        self.data_name = data_name
        self.maximize = maximize
        self.save_best = save_best
        self.min_delta = min_delta
        self.stopping_history: _EvalsLog = {}
        self.current_rounds = 0
        self.best_scores: List[float] = []

    _MAXIMIZE_METRICS = ("auc", "aucpr", "map", "ndcg", "pre")

    def _is_maximize(self, metric: str) -> bool:
        if self.maximize is not None:
            return self.maximize
        base = metric.split("@")[0].split(":")[0]
        return base in self._MAXIMIZE_METRICS

    def after_iteration(self, model, epoch, evals_log) -> bool:
        if not evals_log:
            return False
        data = self.data_name or list(evals_log.keys())[-1]
        log = evals_log[data]
        metric = self.metric_name or list(log.keys())[-1]
        score = log[metric][-1]
        if isinstance(score, (tuple, list)):  # cv (mean, std): stop on mean
            score = score[0]
        maximize = self._is_maximize(metric)
        if not self.best_scores:
            improved = True
        elif maximize:
            improved = score > self.best_scores[-1] + self.min_delta
        else:
            improved = score < self.best_scores[-1] - self.min_delta
        if improved:
            self.best_scores.append(score)
            self.current_rounds = 0
            model.best_iteration = epoch
            model.best_score = score
            model.set_attr(best_iteration=str(epoch), best_score=str(score))
        else:
            self.current_rounds += 1
        return self.current_rounds >= self.rounds

    def after_training(self, model):
        if self.save_best and model.best_iteration is not None and not getattr(model, "_is_cv", False):
            model = model[: model.best_iteration + 1]
        return model

    def state_dict(self) -> dict:
        return {"best_scores": list(self.best_scores),
                "current_rounds": int(self.current_rounds)}

    def load_state(self, state: dict) -> None:
        self.best_scores = [float(s) for s in state.get("best_scores", [])]
        self.current_rounds = int(state.get("current_rounds", 0))


class EvaluationMonitor(TrainingCallback):
    """Log eval results each round (reference: callback.py:511).

    ``rank``: only that rank prints under multi-process training (the
    reference's printer_rank — every worker logging the same line N times
    is noise).  ``show_stdv``: render cv (mean, std) scores as
    ``mean+std``.  ``logger=None`` routes through ``utils.logging``
    (respects ``register_log_callback`` redirection and verbosity=0
    silencing); pass a callable to capture lines directly."""

    def __init__(self, rank: int = 0, period: int = 1, show_stdv: bool = False,
                 logger: Optional[Callable[[str], None]] = None):
        self.printer_rank = int(rank)
        self.period = max(period, 1)
        self.show_stdv = show_stdv
        self.logger = logger
        self._latest: Optional[str] = None

    def _fmt_metric(self, data: str, metric: str, score: _Score) -> str:
        if isinstance(score, (tuple, list)) and len(score) == 2:
            if self.show_stdv:
                return f"\t{data}-{metric}:{score[0]:.5f}+{score[1]:.5f}"
            score = score[0]
        return f"\t{data}-{metric}:{score:.5f}"

    def _emit(self, msg: str) -> None:
        if self.logger is not None:
            self.logger(msg)
        else:
            from .utils import logging as _logging

            _logging.console(msg)

    def after_iteration(self, model, epoch, evals_log) -> bool:
        if not evals_log:
            return False
        from . import collective

        if collective.get_rank() != self.printer_rank:
            return False
        msg = f"[{epoch}]"
        for data, metrics in evals_log.items():
            for metric, hist in metrics.items():
                msg += self._fmt_metric(data, metric, hist[-1])
        if epoch % self.period:
            # off-period round: keep the line so after_training can flush
            # the FINAL scores (reference caches _latest the same way)
            self._latest = msg
        else:
            self._emit(msg)
            self._latest = None
        return False

    def after_training(self, model):
        if self._latest is not None:
            self._emit(self._latest)
            self._latest = None
        return model


class TrainingCheckPoint(TrainingCallback):
    """Save the model every N iterations (reference: callback.py:586)."""

    def __init__(self, directory: str, name: str = "model", as_pickle: bool = False,
                 interval: int = 100):
        import os

        self.dir = directory
        self.name = name
        self.interval = max(interval, 1)
        self.as_pickle = as_pickle
        os.makedirs(directory, exist_ok=True)

    def after_iteration(self, model, epoch, evals_log) -> bool:
        import os
        import pickle

        if epoch % self.interval == 0:
            if self.as_pickle:
                with open(os.path.join(self.dir, f"{self.name}_{epoch}.pkl"), "wb") as fh:
                    pickle.dump(model, fh)
            else:
                model.save_model(os.path.join(self.dir, f"{self.name}_{epoch}.json"))
        return False
