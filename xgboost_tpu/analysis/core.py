"""xtblint core: findings, suppressions, the file/project model, the runner.

The linter is two passes over a fixed rule registry:

1. **Per-file**: every rule's ``check_file`` walks one parsed module and
   may emit findings immediately (retrace hazards, lock discipline,
   nondeterminism) and/or record cross-file *facts* into the shared
   :class:`Project` (seam strings, metric registrations).
2. **Finalize**: rules with a ``finalize`` hook reconcile the collected
   facts against each other and against the documentation contracts
   (``docs/reliability.md`` seam table, ``docs/observability.md`` metrics
   catalog) and emit project-level findings.

Suppressions are comment-driven and line-scoped (tokenized, so strings
containing the marker do not count):

- ``# xtblint: disable=XTB101`` on a line suppresses those codes there;
- ``# xtblint: disable-next=XTB101`` suppresses on the following line;
- ``# xtblint: disable-file=XTB101`` suppresses for the whole file — the
  blanket form, which the repo gate forbids (tests grep for it).

A code entry matches exactly or by family prefix (``XTB2`` covers every
XTB2xx code).  Suppressed findings are *kept* and reported separately in
the JSON report so blanket-silencing shows up in trend tracking instead
of disappearing.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Finding", "SourceFile", "Project", "Rule", "run_lint",
           "lint_paths", "lint_source", "iter_python_files"]

_SUPPRESS_RE = re.compile(
    r"xtblint:\s*(disable(?:-next|-file)?)\s*=\s*([A-Za-z0-9,*\s]+)")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One diagnostic: ``path:line:col: code message``."""

    path: str
    line: int
    col: int
    code: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _match_code(code: str, entries: Sequence[str]) -> bool:
    for e in entries:
        e = e.rstrip("xX") if e.lower().endswith("xx") else e
        if e == "all" or code == e or (e and code.startswith(e)):
            return True
    return False


class _Suppressions:
    """Per-file suppression table parsed from comments."""

    def __init__(self, source: str) -> None:
        self.line: Dict[int, List[str]] = {}
        self.file: List[str] = []
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                kind = m.group(1)
                codes = [c.strip() for c in m.group(2).split(",") if c.strip()]
                if kind == "disable-file":
                    self.file.extend(codes)
                elif kind == "disable-next":
                    self.line.setdefault(tok.start[0] + 1, []).extend(codes)
                else:
                    self.line.setdefault(tok.start[0], []).extend(codes)
        except tokenize.TokenError:  # partial file: no suppressions then
            pass

    def covers(self, line: int, code: str) -> bool:
        if _match_code(code, self.file):
            return True
        return _match_code(code, self.line.get(line, ()))


class SourceFile:
    """One parsed module plus its suppression table."""

    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.suppressions = _Suppressions(source)
        # package-relative path ("serving/batcher.py") when under the
        # xgboost_tpu package, else the basename — rules use it for
        # path-scoped policies without caring where the repo lives
        norm = path.replace(os.sep, "/")
        marker = "xgboost_tpu/"
        idx = norm.rfind(marker)
        self.rel = norm[idx + len(marker):] if idx >= 0 else norm

    def finding(self, node: ast.AST, code: str, message: str) -> Finding:
        return Finding(self.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), code, message)


class Project:
    """Shared state across the per-file pass: collected facts + doc roots."""

    def __init__(self, docs_root: Optional[str] = None) -> None:
        self.docs_root = docs_root
        self.files: List[SourceFile] = []
        self.facts: Dict[str, object] = {}

    def doc_text(self, name: str) -> Optional[str]:
        """Contents of ``docs/<name>`` or None when absent/unset."""
        if not self.docs_root:
            return None
        p = os.path.join(self.docs_root, name)
        if not os.path.isfile(p):
            return None
        with open(p, encoding="utf-8") as fh:
            return fh.read()

    def doc_path(self, name: str) -> str:
        return os.path.join(self.docs_root or "docs", name)


class Rule:
    """Base rule: subclasses set ``name``/``codes`` and override hooks."""

    name: str = ""
    codes: Dict[str, str] = {}

    def check_file(self, sf: SourceFile, project: Project,
                   ) -> Iterable[Finding]:
        return ()

    def finalize(self, project: Project) -> Iterable[Finding]:
        return ()


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        else:
            raise FileNotFoundError(p)
    return out


def _detect_docs_root(paths: Sequence[str]) -> Optional[str]:
    """Walk up from the first scanned path looking for docs/reliability.md
    (the repo layout); fall back to ./docs when run from the repo root."""
    candidates = [os.path.abspath(p) for p in paths] + [os.getcwd()]
    for start in candidates:
        d = start if os.path.isdir(start) else os.path.dirname(start)
        for _ in range(6):
            probe = os.path.join(d, "docs")
            if os.path.isfile(os.path.join(probe, "reliability.md")):
                return probe
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    return None


def _rules() -> List[Rule]:
    # imported here so `import xgboost_tpu.analysis.core` stays cycle-free
    from . import (blocking, envknobs, lockorder, locks, metric_names,
                   nondet, resource_errors, retrace, seams, simd_seam)

    return [retrace.RetraceRule(), locks.LockDisciplineRule(),
            locks.CapiDispatchRule(), seams.SeamConsistencyRule(),
            metric_names.MetricNameRule(), nondet.NondeterminismRule(),
            simd_seam.SimdSeamRule(), blocking.BlockingCallRule(),
            resource_errors.ResourceErrorRule(), lockorder.LockOrderRule(),
            envknobs.EnvKnobRule()]


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    suppressed: List[Finding]
    files_scanned: int
    errors: List[str]

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors


def run_lint(paths: Sequence[str], *, docs_root: Optional[str] = None,
             select: Sequence[str] = (), ignore: Sequence[str] = (),
             ) -> LintResult:
    """Lint ``paths`` (files and/or directories) with every registered rule.

    ``select``/``ignore`` filter by code or family prefix.  Returns every
    finding (suppressed ones split out), sorted by location.
    """
    project = Project(docs_root if docs_root is not None
                      else _detect_docs_root(paths))
    errors: List[str] = []
    for fp in iter_python_files(paths):
        try:
            with open(fp, encoding="utf-8") as fh:
                project.files.append(SourceFile(fp, fh.read()))
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{fp}: cannot parse: {e}")
    rules = _rules()
    raw: List[Finding] = []
    for sf in project.files:
        for rule in rules:
            raw.extend(rule.check_file(sf, project))
    for rule in rules:
        raw.extend(rule.finalize(project))
    if select:
        raw = [f for f in raw if _match_code(f.code, select)]
    if ignore:
        raw = [f for f in raw if not _match_code(f.code, ignore)]
    by_path = {sf.path: sf for sf in project.files}
    findings, suppressed = [], []
    for f in sorted(set(raw)):
        sf = by_path.get(f.path)
        if sf is not None and sf.suppressions.covers(f.line, f.code):
            suppressed.append(f)
        else:
            findings.append(f)
    return LintResult(findings, suppressed, len(project.files), errors)


def lint_paths(paths: Sequence[str], **kw) -> LintResult:
    return run_lint(paths, **kw)


def lint_source(source: str, filename: str = "snippet.py", *,
                docs_root: Optional[str] = None,
                select: Sequence[str] = (), ignore: Sequence[str] = (),
                ) -> LintResult:
    """Lint one in-memory snippet (the self-test entry point): writes
    nothing, runs the full per-file + finalize pipeline on a one-file
    project."""
    project = Project(docs_root)
    project.files.append(SourceFile(filename, source))
    rules = _rules()
    raw: List[Finding] = []
    for rule in rules:
        raw.extend(rule.check_file(project.files[0], project))
    for rule in rules:
        raw.extend(rule.finalize(project))
    if select:
        raw = [f for f in raw if _match_code(f.code, select)]
    if ignore:
        raw = [f for f in raw if not _match_code(f.code, ignore)]
    sup = project.files[0].suppressions
    findings = [f for f in sorted(set(raw)) if not sup.covers(f.line, f.code)]
    suppressed = [f for f in sorted(set(raw)) if sup.covers(f.line, f.code)]
    return LintResult(findings, suppressed, 1, [])


def rule_catalog() -> List[Tuple[str, str, str]]:
    """(code, rule name, description) for every registered code."""
    out = []
    for rule in _rules():
        for code, desc in sorted(rule.codes.items()):
            out.append((code, rule.name, desc))
    return out
