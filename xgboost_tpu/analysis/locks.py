"""XTB2xx — lock discipline: Python lock-owning classes (XTB201) and the
native C-API dispatch-lock contract (XTB202/XTB203).

A class whose ``__init__`` creates a ``threading.Lock`` / ``RLock`` /
``Condition`` (``telemetry/registry.py``, ``serving/batcher.py``,
``serving/registry.py``, ``tracker.py``, ...) has declared that its
instance attributes are shared across threads.  Every *store* to an
instance attribute outside ``__init__`` must then happen under
``with self.<lock>`` — an unguarded ``self.x = ...`` is a data race that
no test reliably reproduces (the ServingMetrics ``compiles_warmup``
setter and the tracker's ``self._conns`` publication were exactly this
before the rule landed).

Attribute-store analysis, per class:

- **lock attributes**: ``self.<name> = threading.Lock()/RLock()/
  Condition(...)`` assignments in ``__init__`` (a Condition wrapping a
  lock counts as a second name for the same guard).
- **stores**: ``self.a = v``, ``self.a += v``, ``del self.a``,
  ``self.a[k] = v``, ``del self.a[k]`` in any other method.  Reads are
  not checked (lock-cheap read paths are a deliberate design here);
  method calls on attributes (``self._q.append``) are not checked —
  flagging them would indict every internally-synchronized member
  (Events, Queues, registry children).
- **guarded**: the store is lexically inside ``with self.<lock>``, or
  the enclosing method is only ever *called from this class* at guarded
  call sites (fixpoint over the intra-class call graph — the
  caller-holds-lock helper pattern: ``MicroBatcher._drain``,
  ``ModelRegistry._evict_for_capacity``).  A method whose reference
  escapes un-called (``threading.Thread(target=self._serve)``) never
  inherits its callers' locks.

The second pass (:class:`CapiDispatchRule`) covers the narrowed C-API
dispatch in ``native/xtb_capi.cc``: since the GIL stopped being the
serializer (jax releases it during XLA execution and the native kernels
are internally threaded), every ``XTB_DLL`` entry point must declare its
dispatch mode — ``API_BEGIN_READ()`` (shared lock, read-only Booster
surface), ``API_BEGIN_MUT()`` (exclusive lock, Booster mutators), or
``API_BEGIN()`` (GIL only, handle-local creation/ingestion).  The rule
text-parses the .cc (no clang needed — the macro discipline IS the
contract) and pins the mode table, so an entry point added without a
guard (XTB202) or a predict-family entry silently downgraded to the
exclusive path — re-serializing concurrent readers — (XTB203) fails the
gate.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Set, Tuple

from .core import Finding, Project, Rule, SourceFile

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}


def _is_lock_ctor(node: ast.expr) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr in _LOCK_FACTORIES
    if isinstance(f, ast.Name):
        return f.id in _LOCK_FACTORIES
    return False


def _self_attr(node: ast.expr) -> str:
    """'a' when node is ``self.a``, else ''."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return ""


def _store_target(node: ast.expr) -> str:
    """Attribute name for a store through self (direct or subscripted)."""
    name = _self_attr(node)
    if name:
        return name
    if isinstance(node, ast.Subscript):
        return _self_attr(node.value)
    return ""


class _MethodScan(ast.NodeVisitor):
    """One method's stores, intra-class call sites, and escaping method
    references, each tagged with whether it sits under ``with self.<lock>``."""

    def __init__(self, lock_attrs: Set[str]) -> None:
        self.lock_attrs = lock_attrs
        self.depth = 0  # with-lock nesting
        self.closure = 0  # nested def/lambda nesting
        self.stores: List[Tuple[ast.AST, str, bool]] = []
        self.calls: List[Tuple[str, bool]] = []   # (method, under_lock)
        self.method_refs: Set[str] = set()        # self.m not in call position

    def _enter_closure(self, node: ast.AST) -> None:
        """A nested def/lambda runs WHENEVER it is later called, not where
        it is written: its body gets no credit for the ambient lock, and a
        ``self.m()`` call inside it counts as an escaping reference (the
        ``Thread(target=lambda: self._serve())`` wrapper pattern)."""
        prev, self.depth = self.depth, 0
        self.closure += 1
        body = node.body if isinstance(node.body, list) else [node.body]
        for stmt in body:
            self.visit(stmt)
        self.closure -= 1
        self.depth = prev

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_closure(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._enter_closure(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._enter_closure(node)

    def _locked_item(self, item: ast.withitem) -> bool:
        return _self_attr(item.context_expr) in self.lock_attrs

    def visit_With(self, node: ast.With) -> None:
        locked = any(self._locked_item(i) for i in node.items)
        self.depth += locked
        self.generic_visit(node)
        self.depth -= locked

    def _record_store(self, target: ast.expr, node: ast.AST) -> None:
        name = _store_target(target)
        if name and name not in self.lock_attrs:
            self.stores.append((node, name, self.depth > 0))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            for el in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                       else [t]):
                self._record_store(el, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_store(node.target, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_store(node.target, node)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._record_store(t, node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        m = _self_attr(node.func)
        if m and self.closure:
            # deferred execution context: treat as an escaping reference,
            # never as a guarded call site
            self.method_refs.add(m)
        elif m:
            self.calls.append((m, self.depth > 0))
        # visit children, but the func attribute itself is a call position
        for child in ast.iter_child_nodes(node):
            if child is node.func and m:
                continue
            self.visit(child)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        m = _self_attr(node)
        if m:
            self.method_refs.add(m)
        self.generic_visit(node)


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    codes = {
        "XTB201": "store to a shared instance attribute outside `with "
                  "self.<lock>` in a lock-owning class",
    }

    def check_file(self, sf: SourceFile, project: Project,
                   ) -> Iterable[Finding]:
        findings: List[Finding] = []
        for cls in ast.walk(sf.tree):
            if isinstance(cls, ast.ClassDef):
                findings.extend(self._check_class(sf, cls))
        return findings

    def _check_class(self, sf: SourceFile, cls: ast.ClassDef,
                     ) -> Iterable[Finding]:
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is None:
            return ()
        lock_attrs: Set[str] = set()
        for node in ast.walk(init):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    name = _self_attr(t)
                    if name:
                        lock_attrs.add(name)
        if not lock_attrs:
            return ()

        methods = [n for n in cls.body if isinstance(n, ast.FunctionDef)
                   and n.name != "__init__"]
        scans: Dict[str, _MethodScan] = {}
        for m in methods:
            scan = _MethodScan(lock_attrs)
            for stmt in m.body:
                scan.visit(stmt)
            scans[m.name] = scan
        # __init__ contributes call sites and escaping references (e.g.
        # Thread(target=self._watch) at construction) but its own stores
        # are exempt — construction happens-before publication
        init_scan = _MethodScan(lock_attrs)
        for stmt in init.body:
            init_scan.visit(stmt)

        # intra-class call graph: method -> [(caller, call under lock)]
        call_sites: Dict[str, List[Tuple[str, bool]]] = {}
        escaped: Set[str] = set()
        for caller, scan in list(scans.items()) + [("__init__", init_scan)]:
            for callee, locked in scan.calls:
                if callee in scans:
                    call_sites.setdefault(callee, []).append((caller, locked))
            for ref in scan.method_refs:
                # method_refs only holds NON-call-position references, so
                # any hit means the method escapes its callers' locks
                if ref in scans:
                    escaped.add(ref)  # e.g. Thread(target=self._serve)

        # fixpoint: a method runs with the lock held when every intra-class
        # call site is under the lock (directly or via a guarded caller) and
        # its reference never escapes without a call
        guarded: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for name in scans:
                if name in guarded or name in escaped:
                    continue
                sites = call_sites.get(name, [])
                if sites and all(locked or caller in guarded
                                 for caller, locked in sites):
                    guarded.add(name)
                    changed = True

        lock_list = "/".join(sorted(lock_attrs))
        findings: List[Finding] = []
        for m in methods:
            if m.name in guarded:
                continue
            for node, attr, locked in scans[m.name].stores:
                if not locked:
                    findings.append(sf.finding(
                        node, "XTB201",
                        f"{cls.name}.{m.name} stores self.{attr} outside "
                        f"`with self.{lock_list}` ({cls.name} owns a lock; "
                        f"unguarded stores race other threads)"))
        return findings


class CapiDispatchRule(Rule):
    """XTB202/XTB203 — the narrowed xtb_capi.cc dispatch-lock contract."""

    name = "capi-dispatch"
    codes = {
        "XTB202": "C-API entry point without a dispatch guard "
                  "(API_BEGIN_READ/API_BEGIN_MUT/API_BEGIN or a manual "
                  "Gil hold)",
        "XTB203": "C-API entry point uses the wrong dispatch mode for its "
                  "contract class (read-only vs mutating)",
    }

    # The contract table (native/xtb_capi.cc CONCURRENCY CONTRACT).  Every
    # name here must carry exactly this macro; unlisted entries may use any
    # guard (new surface starts unclassified, the guard requirement XTB202
    # still applies).
    READ = frozenset({
        "XGBoosterPredict", "XGBoosterPredictFromDMatrix",
        "XGBoosterPredictFromDense", "XGBoosterPredictFromCSR",
        "XGBoosterPredictFromColumnar", "XGBoosterSaveModel",
        "XGBoosterSaveModelToBuffer", "XGBoosterSerializeToBuffer",
        "XGBoosterSaveJsonConfig", "XGBoosterDumpModelEx",
        "XGBoosterDumpModelExWithFeatures", "XGBoosterGetAttr",
        "XGBoosterGetAttrNames", "XGBoosterBoostedRounds",
        "XGBoosterGetNumFeature", "XGBoosterGetStrFeatureInfo",
        "XGBoosterFeatureScore", "XGBoosterGetCategories", "XGBoosterSlice",
    })
    MUT = frozenset({
        "XGBoosterSetParam", "XGBoosterUpdateOneIter",
        "XGBoosterBoostOneIter", "XGBoosterTrainOneIter",
        "XGBoosterEvalOneIter", "XGBoosterLoadModel",
        "XGBoosterLoadModelFromBuffer", "XGBoosterUnserializeFromBuffer",
        "XGBoosterLoadJsonConfig", "XGBoosterReset", "XGBoosterSetAttr",
        "XGBoosterSetStrFeatureInfo",
    })
    # guard-free by design: trivial accessors that never enter Python
    EXEMPT = frozenset({
        "XGBGetLastError", "XGBoostVersion", "XGBRegisterLogCallback",
    })

    # return types may span several tokens (`const char*`); the entry-point
    # name is the last identifier before the parameter list
    _DEF_RE = re.compile(r"XTB_DLL\s+(?:[\w:]+[\s*&]+)+(\w+)\s*\(")

    def capi_path(self, project: Project) -> str:
        if not project.docs_root:
            return ""
        return os.path.join(os.path.dirname(project.docs_root), "native",
                            "xtb_capi.cc")

    def finalize(self, project: Project) -> Iterable[Finding]:
        path = self.capi_path(project)
        if not path or not os.path.isfile(path):
            return ()  # subtree lint / snippet mode: nothing to check
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
        return self.check_text(text, path)

    def check_text(self, text: str, path: str) -> List[Finding]:
        findings: List[Finding] = []
        defs = list(self._DEF_RE.finditer(text))
        for i, m in enumerate(defs):
            name = m.group(1)
            end = defs[i + 1].start() if i + 1 < len(defs) else len(text)
            body = text[m.end():end]
            line = text.count("\n", 0, m.start()) + 1
            if "API_BEGIN_READ()" in body:
                mode = "read"
            elif "API_BEGIN_MUT()" in body:
                mode = "mut"
            elif "API_BEGIN()" in body or "Gil gil" in body:
                mode = "gil"
            elif re.search(r"return\s+XG\w+\s*\(", body):
                mode = "delegate"  # thin alias: the callee carries the guard
            else:
                mode = None
            if mode is None and name not in self.EXEMPT:
                findings.append(Finding(
                    path, line, 0, "XTB202",
                    f"{name} has no dispatch guard (API_BEGIN_READ/"
                    f"API_BEGIN_MUT/API_BEGIN) and does not delegate"))
                continue
            want = ("read" if name in self.READ
                    else "mut" if name in self.MUT else None)
            if want is not None and mode not in (want, "delegate"):
                findings.append(Finding(
                    path, line, 0, "XTB203",
                    f"{name} must use API_BEGIN_{want.upper()}() per the "
                    f"dispatch contract, found "
                    f"{mode if mode else 'no guard'}"))
        return findings
