"""XTB3xx — fault-seam consistency.

The fault-injection harness (``reliability/faults.py``) and its users
agree only through *strings*: a seam fires because some call site passes
``maybe_inject("train.round")`` and a fault plan names the same string.
Nothing at runtime ever cross-checks the set — a typo'd seam silently
never fires, and a seam removed from code leaves plans and docs pointing
at nothing.  This rule makes ``faults.SEAMS`` the single source of truth:

- **XTB301** — a ``maybe_inject("...")`` call site uses a seam name that
  is not in ``SEAMS`` (typo or undeclared seam);
- **XTB302** — a ``SEAMS`` member no call site ever injects (dead seam:
  plans targeting it silently no-op);
- **XTB303** — a ``SEAMS`` member missing from the seam table in
  ``docs/reliability.md`` (the documented operator contract);
- **XTB304** — ``maybe_inject`` called with a non-literal seam name
  (dynamic names defeat every static check, including this one).

When the scanned set does not include a ``SEAMS`` definition (linting a
subtree), the cross-checks are skipped — per-file XTB304 still applies.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Finding, Project, Rule, SourceFile

_FACT_USES = "seams.uses"       # list[(seam, path, line, col)]
_FACT_DECL = "seams.declared"   # (set[str], path, line)


def _call_tail(func: ast.expr) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _frozenset_literal(node: ast.expr) -> Optional[Set[str]]:
    """String members of ``frozenset({...})`` / ``frozenset((...))``."""
    if not (isinstance(node, ast.Call)
            and _call_tail(node.func) == "frozenset" and node.args):
        return None
    arg = node.args[0]
    if isinstance(arg, (ast.Set, ast.Tuple, ast.List)):
        out = set()
        for el in arg.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None
            out.add(el.value)
        return out
    return None


class SeamConsistencyRule(Rule):
    name = "seam-consistency"
    codes = {
        "XTB301": "maybe_inject() seam name not declared in faults.SEAMS",
        "XTB302": "declared seam never injected anywhere (dead seam)",
        "XTB303": "declared seam missing from the docs/reliability.md "
                  "seam table",
        "XTB304": "maybe_inject() with a non-literal seam name",
    }

    def check_file(self, sf: SourceFile, project: Project,
                   ) -> Iterable[Finding]:
        findings: List[Finding] = []
        uses = project.facts.setdefault(_FACT_USES, [])
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Call)
                    and _call_tail(node.func).endswith("maybe_inject")):
                if not node.args:
                    continue
                arg = node.args[0]
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    uses.append((arg.value, sf.path, node.lineno,
                                 node.col_offset))
                else:
                    findings.append(sf.finding(
                        node, "XTB304",
                        "maybe_inject() seam name must be a string literal "
                        "(dynamic names cannot be checked against SEAMS)"))
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "SEAMS":
                        members = _frozenset_literal(node.value)
                        if members is not None:
                            project.facts[_FACT_DECL] = (
                                members, sf.path, node.lineno)
        return findings

    def finalize(self, project: Project) -> Iterable[Finding]:
        decl = project.facts.get(_FACT_DECL)
        uses: List[Tuple[str, str, int, int]] = (
            project.facts.get(_FACT_USES) or [])
        if decl is None:
            return ()
        seams, decl_path, decl_line = decl
        findings: List[Finding] = []
        used_names: Dict[str, None] = {}
        for seam, path, line, col in uses:
            used_names.setdefault(seam)
            if seam not in seams:
                findings.append(Finding(
                    path, line, col, "XTB301",
                    f"seam {seam!r} is not declared in faults.SEAMS "
                    f"(typo, or add it to the canonical set + docs)"))
        for seam in sorted(seams - set(used_names)):
            findings.append(Finding(
                decl_path, decl_line, 0, "XTB302",
                f"seam {seam!r} is declared in SEAMS but no "
                f"maybe_inject() call site fires it (dead seam)"))
        doc = project.doc_text("reliability.md")
        if doc is not None:
            for seam in sorted(seams):
                if seam not in doc:
                    findings.append(Finding(
                        decl_path, decl_line, 0, "XTB303",
                        f"seam {seam!r} is not documented in "
                        f"{project.doc_path('reliability.md')}"))
        return findings
