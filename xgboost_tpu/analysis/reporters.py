"""xtblint output: human text and machine JSON (trend-tracking shape).

The JSON report is what ``scripts/lint_gate.sh`` persists into
``bench_out/lint_report.json`` — findings AND suppressed findings, so a
suppression added to silence a rule shows up in the trend instead of
vanishing.
"""
from __future__ import annotations

import json
from collections import Counter
from typing import List

from .core import LintResult

TOOL = "xtblint"
VERSION = "1.0"


def render_text(result: LintResult, *, verbose: bool = False) -> str:
    lines: List[str] = [f.render() for f in result.findings]
    lines.extend(f"ERROR {e}" for e in result.errors)
    if verbose and result.suppressed:
        lines.extend(f"suppressed: {f.render()}" for f in result.suppressed)
    n = len(result.findings)
    summary = (f"{TOOL}: {n} finding{'s' if n != 1 else ''}, "
               f"{len(result.suppressed)} suppressed, "
               f"{result.files_scanned} files scanned")
    if result.errors:
        summary += f", {len(result.errors)} errors"
    lines.append(summary)
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    counts = Counter(f.code for f in result.findings)
    payload = {
        "tool": TOOL,
        "version": VERSION,
        "clean": result.clean,
        "files_scanned": result.files_scanned,
        "counts": dict(sorted(counts.items())),
        "findings": [f.as_dict() for f in result.findings],
        "suppressed": [f.as_dict() for f in result.suppressed],
        "errors": list(result.errors),
    }
    return json.dumps(payload, indent=2, sort_keys=False) + "\n"
