"""XTB5xx — nondeterminism in reproducible code paths.

Training is contractually bit-reproducible (quantised histograms, relay
collectives, kill/resume parity tests), which makes wall-clock reads and
unseeded RNG in those paths latent reproducibility bugs even when today's
call sites look harmless:

- **XTB501** — ``time.time()``: wall clock, steps on NTP adjustments and
  is not monotonic.  Timing code here uses ``time.monotonic()`` /
  ``time.perf_counter_ns()``; scheduling uses deadlines derived from
  monotonic clocks.  (``time.sleep`` is fine — duration, not a reading.)
- **XTB502** — module-level ``random.*`` / ``np.random.*`` convenience
  functions draw from ambient global state no test controls.  The
  sanctioned forms are explicit seeded generators:
  ``random.Random(seed)`` (retry jitter, seeded per rank/op) and
  ``np.random.default_rng(seed)`` / ``Generator`` / ``SeedSequence``
  (column sampling, test data).

Scope: the whole package except ``testing/`` (fixture helpers may be
time-seeded) and ``analysis/`` (the linter itself).  The sanctioned
constructors are allowed *everywhere* — the rule flags ambient-state
draws, not randomness.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from .core import Finding, Project, Rule, SourceFile

_EXEMPT_PREFIXES = ("testing/", "analysis/")

_RANDOM_MODULE_FNS = {
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "lognormvariate",
    "expovariate", "vonmisesvariate", "paretovariate", "weibullvariate",
    "betavariate", "triangular", "getrandbits", "randbytes", "seed",
}
_NP_RANDOM_ALLOWED = {
    "default_rng", "Generator", "SeedSequence", "PCG64", "Philox",
    "MT19937", "BitGenerator",
}
_NUMPY_ALIASES = {"np", "numpy", "onp"}


class NondeterminismRule(Rule):
    name = "nondeterminism"
    codes = {
        "XTB501": "time.time() in a reproducible code path (use "
                  "time.monotonic()/perf_counter_ns())",
        "XTB502": "ambient-state RNG (random.* / np.random.*) in a "
                  "reproducible code path (use a seeded generator)",
    }

    def check_file(self, sf: SourceFile, project: Project,
                   ) -> Iterable[Finding]:
        if sf.rel.startswith(_EXEMPT_PREFIXES):
            return ()
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Attribute):
                continue
            base = node.value
            if (isinstance(base, ast.Name) and base.id == "time"
                    and node.attr == "time"):
                findings.append(sf.finding(
                    node, "XTB501",
                    "time.time() is wall-clock (non-monotonic, NTP-"
                    "steppable); use time.monotonic() or "
                    "time.perf_counter_ns()"))
            elif (isinstance(base, ast.Name) and base.id == "random"
                  and node.attr in _RANDOM_MODULE_FNS):
                findings.append(sf.finding(
                    node, "XTB502",
                    f"random.{node.attr} draws from the ambient global "
                    f"RNG; use an explicit random.Random(seed) instance"))
            elif (isinstance(base, ast.Attribute)
                  and base.attr == "random"
                  and isinstance(base.value, ast.Name)
                  and base.value.id in _NUMPY_ALIASES
                  and node.attr not in _NP_RANDOM_ALLOWED):
                findings.append(sf.finding(
                    node, "XTB502",
                    f"np.random.{node.attr} uses the legacy global RNG; "
                    f"use np.random.default_rng(seed)"))
        return findings
