"""XTB4xx — ``xtb_*`` metric-name consistency.

The telemetry registry keys families by *string name*; serving,
reliability, and telemetry modules each register their own series, and
the docs promise operators a stable catalog.  Three drift modes, each a
code:

- **XTB401** — a registered metric missing from the metrics catalog in
  ``docs/observability.md`` (operators scrape names they can't look up);
- **XTB402** — the same name registered with a conflicting kind or label
  set (the registry raises at runtime — but only when the *second*
  registration happens to run, typically in production);
- **XTB403** — a metric-shaped ``xtb_*`` name mentioned in code
  docstrings/strings or in the docs that no code registers (a renamed or
  deleted series leaving dangling references — dashboards built from
  those mentions silently flatline).

"Metric-shaped" filters the package's other ``xtb_`` namespaces (native
kernel symbols like ``xtb_csr_rows``): a token counts only when it ends
with a Prometheus-convention suffix (``_total``, ``_seconds``, ...) and
does not carry a native symbol prefix (``xtb_csr_`` etc., the
``utils/native.py`` / ``native/`` C symbol families).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Tuple

from .core import Finding, Project, Rule, SourceFile

_FACT_REG = "metrics.registrations"  # name -> [(kind, labels, path, line)]
_FACT_MENTION = "metrics.mentions"   # list[(token, path, line)]

_REG_METHODS = {"counter", "gauge", "histogram"}
_TOKEN_RE = re.compile(r"\bxtb_[a-z0-9_]+")
# Prometheus-convention endings that make an xtb_ token a metric name
_METRIC_SUFFIXES = ("_total", "_seconds", "_bytes", "_rows", "_peak",
                    "_steady", "_warmup", "_count", "_sum", "_bucket",
                    "_info", "_ratio")
# the package's non-metric xtb_ namespaces (native C symbols + sources)
_NATIVE_PREFIXES = ("xtb_csr_", "xtb_dense_", "xtb_summary_", "xtb_parse_",
                    "xtb_native", "xtb_ffi", "xtb_kernels", "xtb_capi",
                    "xtb_hist", "xtb_split", "xtb_predict", "xtb_lambdarank")
_DOCS = ("observability.md", "reliability.md", "serving.md")


def _literal_labels(node: ast.Call) -> Optional[Tuple[str, ...]]:
    """Label-name tuple when given literally; None when absent/dynamic."""
    arg = None
    if len(node.args) >= 3:
        arg = node.args[2]
    else:
        for kw in node.keywords:
            if kw.arg == "label_names":
                arg = kw.value
    if arg is None:
        return ()
    if isinstance(arg, (ast.Tuple, ast.List)):
        out = []
        for el in arg.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def _metric_shaped(token: str) -> bool:
    if token.startswith(_NATIVE_PREFIXES):
        return False
    return token.endswith(_METRIC_SUFFIXES)


def _derived_names(name: str, kind: str) -> List[str]:
    if kind == "histogram":
        return [name, name + "_bucket", name + "_sum", name + "_count"]
    return [name]


class MetricNameRule(Rule):
    name = "metric-names"
    codes = {
        "XTB401": "registered xtb_* metric missing from the "
                  "docs/observability.md metrics catalog",
        "XTB402": "metric name registered with conflicting kind or labels",
        "XTB403": "metric-shaped xtb_* name mentioned but never registered",
    }

    def check_file(self, sf: SourceFile, project: Project,
                   ) -> Iterable[Finding]:
        regs: Dict[str, list] = project.facts.setdefault(_FACT_REG, {})
        mentions: list = project.facts.setdefault(_FACT_MENTION, [])
        # module-level string constants (PHASE_HISTOGRAM = "xtb_...") so a
        # registration through a named constant still resolves
        consts: Dict[str, str] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Name)
                            and isinstance(node.value, ast.Constant)
                            and isinstance(node.value.value, str)):
                        consts[t.id] = node.value.value
        for node in ast.walk(sf.tree):
            name = None
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REG_METHODS
                    and node.args):
                arg0 = node.args[0]
                if (isinstance(arg0, ast.Constant)
                        and isinstance(arg0.value, str)):
                    name = arg0.value
                elif isinstance(arg0, ast.Name):
                    name = consts.get(arg0.id)
            if name is not None and name.startswith("xtb_"):
                regs.setdefault(name, []).append(
                    (node.func.attr, _literal_labels(node), sf.path,
                     node.lineno))
            elif (isinstance(node, ast.Constant)
                  and isinstance(node.value, str)):
                for token in _TOKEN_RE.findall(node.value):
                    mentions.append((token, sf.path, node.lineno))
        return ()

    def finalize(self, project: Project) -> Iterable[Finding]:
        regs: Dict[str, list] = project.facts.get(_FACT_REG) or {}
        mentions: List[Tuple[str, str, int]] = (
            project.facts.get(_FACT_MENTION) or [])
        findings: List[Finding] = []

        # XTB402: one signature per name across every registration site
        for name, sites in sorted(regs.items()):
            kinds = {k for k, _l, _p, _ln in sites}
            labels = {l for _k, l, _p, _ln in sites if l is not None}
            if len(kinds) > 1 or len(labels) > 1:
                first = sites[0]
                for kind, lab, path, line in sites[1:]:
                    findings.append(Finding(
                        path, line, 0, "XTB402",
                        f"metric {name!r} registered as {kind}{lab} here "
                        f"but as {first[0]}{first[1]} at "
                        f"{first[2]}:{first[3]} (the registry raises on "
                        f"the second registration at runtime)"))

        # known = every registered family plus histogram exposition series
        known = set()
        for name, sites in regs.items():
            for kind, _labels, _p, _ln in sites:
                known.update(_derived_names(name, kind))

        # XTB401: every registered family must be in the docs catalog
        obs = project.doc_text("observability.md")
        if obs is not None:
            for name, sites in sorted(regs.items()):
                if name not in obs:
                    kind, _labels, path, line = sites[0]
                    findings.append(Finding(
                        path, line, 0, "XTB401",
                        f"metric {name!r} ({kind}) is not documented in "
                        f"{project.doc_path('observability.md')} — add it "
                        f"to the metrics catalog"))

        # XTB403: metric-shaped mentions (code strings + docs) must resolve
        if regs:
            doc_mentions: List[Tuple[str, str, int]] = []
            for doc in _DOCS:
                text = project.doc_text(doc)
                if text is None:
                    continue
                for i, line_text in enumerate(text.splitlines(), start=1):
                    for token in _TOKEN_RE.findall(line_text):
                        doc_mentions.append(
                            (token, project.doc_path(doc), i))
            for token, path, line in mentions + doc_mentions:
                if _metric_shaped(token) and token not in known:
                    findings.append(Finding(
                        path, line, 0, "XTB403",
                        f"{token!r} looks like a metric name but nothing "
                        f"registers it (renamed series? native symbol "
                        f"missing from the prefix allowlist?)"))
        return findings
