"""XTB7xx — unbounded blocking calls (the watchdog's static complement).

The stall watchdog (``reliability/watchdog.py``) can only escalate a
wedge it can *see*: an operation bracketed by a guard or bounded by a
timeout eventually surfaces somewhere, but a bare ``Event.wait()``,
``Queue.get()``, ``Future.result()``, or un-timed socket connect blocks
a thread forever with nothing watching — the exact hang class the
watchdog plane exists to eliminate.  This rule family rejects them
textually (the XTB202 approach: the call shape IS the contract):

- **XTB701** — ``<expr>.wait()`` with no arguments and no ``timeout=``
  (Event/Condition/Barrier/``Popen.wait`` all block unbounded in this
  form).  An explicit ``timeout=None`` is allowed: deliberately-forever
  waits must SAY so (the tracker abort watchers do).
- **XTB702** — an unbounded blocking consume: zero-argument
  ``.result()`` (concurrent.futures), or zero-argument ``.get()`` on a
  queue-named receiver (``q``, ``queue``, ``*_queue`` — plain
  ``dict.get``/gauge reads don't match).
- **XTB703** — ``socket.create_connection(addr)`` without a timeout
  (second positional argument or ``timeout=``): the OS-level connect
  can block for minutes on a black-holed route.

The watchdog module itself is exempt — it is the one place allowed to
own blocking primitives, because it is the thing doing the watching.
Everything else either bounds the call or routes it through a guard.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from .core import Finding, Project, Rule, SourceFile

# the one module allowed to block unbounded (package-relative path)
_EXEMPT_FILES = ("reliability/watchdog.py",)

_QUEUEISH = ("q", "queue")


def _call_tail(func: ast.expr) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _receiver_tail(func: ast.expr) -> str:
    """Name of the object a method is called on ('x' for x.get, 'b' for
    a.b.get), lower-cased; '' when unnameable."""
    if not isinstance(func, ast.Attribute):
        return ""
    v = func.value
    if isinstance(v, ast.Name):
        return v.id.lower()
    if isinstance(v, ast.Attribute):
        return v.attr.lower()
    return ""


def _has_kwarg(node: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in node.keywords)


def _queueish(name: str) -> bool:
    base = name.lstrip("_")
    return base in _QUEUEISH or base.endswith("_queue") or base == "queue"


class BlockingCallRule(Rule):
    name = "blocking-calls"
    codes = {
        "XTB701": "unbounded .wait() — no argument and no timeout= "
                  "(Event/Condition/Barrier/Popen block forever here)",
        "XTB702": "unbounded blocking consume — zero-arg .result(), or "
                  "zero-arg .get() on a queue-named receiver",
        "XTB703": "socket.create_connection without a timeout (the "
                  "connect can black-hole for minutes)",
    }

    def check_file(self, sf: SourceFile, project: Project,
                   ) -> Iterable[Finding]:
        if sf.rel in _EXEMPT_FILES:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = _call_tail(node.func)
            if (tail == "wait" and not node.args
                    and not _has_kwarg(node, "timeout")):
                findings.append(sf.finding(
                    node, "XTB701",
                    "unbounded .wait(): pass a timeout (or an explicit "
                    "timeout=None if blocking forever is the design) — "
                    "an unwatched wait is the hang class the watchdog "
                    "plane exists to kill"))
            elif (tail == "result" and not node.args
                    and not _has_kwarg(node, "timeout")):
                findings.append(sf.finding(
                    node, "XTB702",
                    "unbounded Future.result(): poll with "
                    "result(timeout=...) under a watchdog guard so a "
                    "wedged producer is a detected stall, not a hang"))
            elif (tail == "get" and not node.args and not node.keywords
                    and _queueish(_receiver_tail(node.func))):
                findings.append(sf.finding(
                    node, "XTB702",
                    "unbounded queue .get(): pass a timeout (block "
                    "forever only via an explicit, watched wait)"))
            elif (tail == "create_connection" and len(node.args) < 2
                    and not _has_kwarg(node, "timeout")):
                findings.append(sf.finding(
                    node, "XTB703",
                    "socket.create_connection without a timeout: bound "
                    "the connect so a black-holed route is a detected "
                    "fault"))
        return findings
