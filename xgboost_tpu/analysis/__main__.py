"""``python -m xgboost_tpu.analysis`` — the xtblint CLI.

Exit-code contract (what the CI gate keys on):

- **0** — no findings (suppressed findings do not fail the gate; they are
  reported so trends catch suppression creep);
- **1** — at least one finding (or an unparseable file);
- **2** — usage error / unknown path.

Typical invocations::

    python -m xgboost_tpu.analysis xgboost_tpu/
    python -m xgboost_tpu.analysis xgboost_tpu/ --format json \
        --json-out bench_out/lint_report.json
    python -m xgboost_tpu.analysis --list-rules
    python -m xgboost_tpu.analysis xgboost_tpu/serving --select XTB2
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .core import rule_catalog, run_lint
from .reporters import render_json, render_text


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m xgboost_tpu.analysis",
        description="xtblint: project-native static analysis for retrace "
                    "hazards (XTB1xx), lock discipline (XTB2xx), fault-seam "
                    "consistency (XTB3xx), metric-name consistency "
                    "(XTB4xx), nondeterminism (XTB5xx), SIMD confinement "
                    "(XTB6xx), unbounded blocking calls (XTB7xx), lock-order "
                    "and blocking-under-lock discipline (XTB901-903), and "
                    "the env-knob catalog (XTB905/XTB906).")
    p.add_argument("paths", nargs="*", help="files/directories to lint "
                   "(default: ./xgboost_tpu)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--json-out", metavar="FILE",
                   help="also write the JSON report here (any --format)")
    p.add_argument("--select", action="append", default=[],
                   metavar="CODES", help="only these codes/families "
                   "(comma-separated; e.g. XTB2,XTB301)")
    p.add_argument("--ignore", action="append", default=[],
                   metavar="CODES", help="drop these codes/families")
    p.add_argument("--docs", metavar="DIR",
                   help="docs directory for the XTB3xx/XTB4xx contracts "
                   "(default: auto-detected docs/ next to the package)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="also print suppressed findings")
    return p


def _split(entries: List[str]) -> List[str]:
    out: List[str] = []
    for e in entries:
        out.extend(c.strip() for c in e.split(",") if c.strip())
    return out


def main(argv: Optional[List[str]] = None) -> int:
    args = _parser().parse_args(argv)
    if args.list_rules:
        for code, rule, desc in rule_catalog():
            print(f"{code}  [{rule}] {desc}")
        return 0
    paths = args.paths or ["xgboost_tpu"]
    for p in paths:
        if not os.path.exists(p):
            print(f"xtblint: no such path: {p}", file=sys.stderr)
            return 2
    try:
        result = run_lint(paths, docs_root=args.docs,
                          select=_split(args.select),
                          ignore=_split(args.ignore))
    except FileNotFoundError as e:  # racing deletion mid-walk
        print(f"xtblint: {e}", file=sys.stderr)
        return 2
    if args.json_out:
        os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
        with open(args.json_out, "w", encoding="utf-8") as fh:
            fh.write(render_json(result))
    if args.format == "json":
        sys.stdout.write(render_json(result))
    else:
        print(render_text(result, verbose=args.verbose))
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
