"""XTB8xx — silent OS-error swallows in resource-critical modules.

The resource-pressure audit (docs/reliability.md "Resource pressure &
graceful degradation") found the failure pattern behind most "mystery"
degradations: an ``except OSError: pass`` at a write/close/cleanup site.
ENOSPC on a checkpoint, EMFILE on an accept loop, and EBADF on a routine
shutdown close all vanish into the same two lines — so the one errno that
*mattered* was indistinguishable from the noise, and the first visible
symptom of a full disk was a crash three subsystems away.

**XTB801**: in the ``reliability/``, ``serving/``, and ``data/`` modules,
an ``except`` handler that catches bare ``OSError`` (or ``IOError`` /
``EnvironmentError``, or a tuple containing one) must do at least one of:

- **re-raise** (``raise`` anywhere in the handler body);
- **route through the governor** — call ``note_os_error(...)`` /
  ``degrade(...)`` (``reliability/resources.py`` classifies the errno
  into ``xtb_resource_errors_total{errno,site}`` and degrades the
  matching resource level);
- **increment a telemetry counter** (an ``.inc(...)`` call);
- **surface the caught exception** — bind it (``as e``) and pass it into
  some call (a warning, a death path, a wrapped re-raise), so the error
  object leaves the handler instead of dying in it.

Handlers doing none of these are *silent swallows* and fail the gate.
Narrow catches (``except FileNotFoundError``) are exempt: naming the
precise expected errno IS the classification — the rule targets the
catch-all shape that conflates "expected" with "out of disk".
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from .core import Finding, Project, Rule, SourceFile

# package-relative path prefixes in scope: the modules that own storage,
# sockets, and spill files — where an errno is load-bearing
_SCOPE_PREFIXES = ("reliability/", "serving/", "data/")

# bare catch-all names the rule triggers on (IOError/EnvironmentError are
# OSError aliases since 3.3)
_BROAD_NAMES = {"OSError", "IOError", "EnvironmentError"}

# calls that count as routing/counting: the governor funnel, the
# telemetry counter increment shape, and the integrity accounting funnel
# (reliability/integrity.py — those ARE labeled counters)
_ROUTING_CALLS = {"note_os_error", "degrade", "inc",
                  "corrupt_detected", "quarantined", "retried", "scrubbed"}


def _name_tail(node: ast.expr) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _catches_broad_oserror(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return False  # bare `except:` is XTB-agnostic (and already rare)
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(_name_tail(x) in _BROAD_NAMES for x in types)


def _handler_compliant(handler: ast.ExceptHandler) -> bool:
    bound = handler.name  # `except OSError as e` -> "e"; None when unbound
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                if _name_tail(node.func) in _ROUTING_CALLS:
                    return True
                if bound is not None:
                    # does the caught exception flow INTO this call?
                    for part in list(node.args) + [
                            kw.value for kw in node.keywords]:
                        for sub in ast.walk(part):
                            if (isinstance(sub, ast.Name)
                                    and sub.id == bound):
                                return True
    return False


class ResourceErrorRule(Rule):
    name = "resource-errors"
    codes = {
        "XTB801": "bare `except OSError` in reliability/serving/data must "
                  "re-raise, route through the resource governor "
                  "(note_os_error/degrade), increment a counter, or pass "
                  "the caught error to a call — no silent swallows",
    }

    def check_file(self, sf: SourceFile, project: Project,
                   ) -> Iterable[Finding]:
        if not sf.rel.startswith(_SCOPE_PREFIXES):
            return ()
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _catches_broad_oserror(node):
                continue
            if _handler_compliant(node):
                continue
            findings.append(sf.finding(
                node, "XTB801",
                "silent OSError swallow: classify it "
                "(reliability.resources.note_os_error(e, site)), count "
                "it, re-raise it, or narrow the except to the precise "
                "expected subclass — an ENOSPC dropped here surfaces "
                "three subsystems away as a mystery crash"))
        return findings
