"""xtblint — project-native static analysis for the xgboost_tpu tree.

The reference C++ stack leans on compiler warnings, clang-tidy, and
sanitizer CI; a JAX port gets none of that for its real invariants.
This package is the replacement: an AST-level linter with five rule
families grounded in this codebase's contracts —

- **XTB1xx** retrace/host-sync hazards inside ``jax.jit``/``pallas_call``
  bodies (the thing ``xtb_compiles_total`` only catches at runtime);
- **XTB2xx** lock discipline in thread-shared classes (telemetry
  registry, serving batcher/registry, tracker);
- **XTB3xx** fault-seam string consistency against ``faults.SEAMS`` and
  ``docs/reliability.md``;
- **XTB4xx** ``xtb_*`` metric-name consistency against the registry and
  the ``docs/observability.md`` catalog;
- **XTB5xx** nondeterminism (wall-clock reads, ambient-state RNG) in
  reproducible paths.

CLI: ``python -m xgboost_tpu.analysis xgboost_tpu/`` (exit 0 = clean —
the pre-merge gate run by ``scripts/lint_gate.sh`` and the quick test
tier).  Suppress a line with ``# xtblint: disable=XTB201``; see
``docs/static_analysis.md`` for the rule catalog and how to add a rule.
"""
from .core import (Finding, LintResult, lint_paths, lint_source,
                   rule_catalog, run_lint)
from .reporters import render_json, render_text

__all__ = ["Finding", "LintResult", "lint_paths", "lint_source",
           "run_lint", "rule_catalog", "render_json", "render_text"]
