"""XTB1xx — retrace / host-sync hazards inside traced function bodies.

A ``@jax.jit`` (or ``pallas_call``) body runs under tracing: any call that
needs a *concrete* value — ``float()``/``int()``/``bool()`` on a traced
array, ``.item()``/``.tolist()``, ``jax.device_get``, or a ``np.*``
function over traced operands — either blocks on a device sync (silently
serializing the hot path) or raises ``TracerArrayConversionError`` only
on the first input that takes that branch.  Both failure modes are
exactly what ``xtb_compiles_total`` / ``xtb_compiles_steady`` catch at
runtime; this rule catches them pre-merge.

Traced bodies are found lexically, per file:

- functions decorated with ``jit`` / ``jax.jit`` /
  ``functools.partial(jax.jit, ...)``;
- local/module functions and lambdas referenced by name anywhere inside a
  ``jax.jit(...)`` or ``pallas_call(...)`` call expression (covers the
  ``self._fn = jax.jit(_shard_map(fn, ...))`` pattern in
  ``parallel/grower.py``);
- functions nested inside a traced body (they execute during the trace).

Host-side work on *statically known* values is allowed — that is how the
FFI entry points legitimately pass ``np.int32(k)`` attributes.  Static
means: constants, ``static_argnames``/``static_argnums`` parameters,
``len(...)``, ``.shape``/``.ndim``/``.size``/``.dtype`` expressions,
``x is (not) None`` checks (concrete at trace time), and locals assigned
only from static expressions (a small per-function dataflow fixpoint).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from .core import Finding, Project, Rule, SourceFile

_JIT_NAMES = {"jit"}
_PALLAS_NAMES = {"pallas_call"}
_HOST_SYNC_BUILTINS = {"float", "int", "bool"}
_HOST_PULL_METHODS = {"item", "tolist"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}
_STATIC_BUILTINS = {"len", "int", "float", "bool", "max", "min", "round",
                    "abs", "range", "tuple", "str", "isinstance", "getattr",
                    "hasattr"}
_NUMPY_ALIASES = {"np", "numpy", "onp"}


def _attr_tail(node: ast.expr) -> str:
    """Last component of a Name/Attribute chain ('' otherwise)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_jit_expr(func: ast.expr) -> bool:
    """``jit`` / ``jax.jit`` / ``partial(jax.jit, ...)`` as a callee."""
    if _attr_tail(func) in _JIT_NAMES:
        return True
    if isinstance(func, ast.Call) and _attr_tail(func.func) == "partial":
        return any(_attr_tail(a) in _JIT_NAMES for a in func.args[:1])
    return False


def _is_tracing_call(call: ast.Call) -> bool:
    return (_is_jit_expr(call.func)
            or _attr_tail(call.func) in _PALLAS_NAMES)


def _static_params_from_jit(call: ast.Call, fn: Optional[ast.AST],
                            ) -> Set[str]:
    """Parameter names pinned static by ``static_argnames``/``static_argnums``
    keywords of a jit/partial call."""
    out: Set[str] = set()
    argnames: List[str] = []
    if fn is not None and isinstance(fn, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
        argnames = [a.arg for a in (fn.args.posonlyargs + fn.args.args)]
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    out.add(v.value)
        elif kw.arg == "static_argnums":
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                if (isinstance(v, ast.Constant)
                        and isinstance(v.value, int)
                        and 0 <= v.value < len(argnames)):
                    out.add(argnames[v.value])
    return out


def _is_numpy_call(func: ast.expr) -> bool:
    """Any ``np.<...>(...)`` / ``numpy.<...>(...)`` callee, including
    nested chains like ``np.linalg.norm``."""
    node = func
    while isinstance(node, ast.Attribute):
        node = node.value
    return isinstance(node, ast.Name) and node.id in _NUMPY_ALIASES


class _StaticEnv:
    """Static-expression oracle for one traced function: the pinned static
    parameters plus locals assigned only from static expressions."""

    def __init__(self, fn: ast.AST, static_params: Set[str]) -> None:
        self.names: Set[str] = set(static_params)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        assigns: Dict[str, List[ast.expr]] = {}
        targets_seen: Set[str] = set()
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            assigns.setdefault(t.id, []).append(node.value)
                            targets_seen.add(t.id)
                        else:  # tuple unpack etc: give up on those names
                            for el in ast.walk(t):
                                if isinstance(el, ast.Name):
                                    targets_seen.add(el.id)
                                    assigns.setdefault(el.id, []).append(
                                        None)  # type: ignore[arg-type]
                elif isinstance(node, (ast.AugAssign, ast.For)):
                    t = node.target
                    for el in ast.walk(t):
                        if isinstance(el, ast.Name):
                            assigns.setdefault(el.id, []).append(
                                None)  # type: ignore[arg-type]
        changed = True
        while changed:
            changed = False
            for name, values in assigns.items():
                if name in self.names:
                    continue
                if all(v is not None and self.is_static(v) for v in values):
                    self.names.add(name)
                    changed = True

    def is_static(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Attribute):
            return node.attr in _STATIC_ATTRS or self.is_static(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_static(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_static(node.left) and self.is_static(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_static(node.operand)
        if isinstance(node, ast.BoolOp):
            return all(self.is_static(v) for v in node.values)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return True  # identity checks are concrete at trace time
            return (self.is_static(node.left)
                    and all(self.is_static(c) for c in node.comparators))
        if isinstance(node, ast.IfExp):
            return (self.is_static(node.body)
                    and self.is_static(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List)):
            return all(self.is_static(e) for e in node.elts)
        if isinstance(node, ast.Call):
            tail = _attr_tail(node.func)
            if tail in _STATIC_BUILTINS and isinstance(node.func, ast.Name):
                return all(self.is_static(a) for a in node.args)
            return False
        return False


class RetraceRule(Rule):
    name = "retrace-hazards"
    codes = {
        "XTB101": "host-sync builtin (float/int/bool) on a traced value "
                  "inside a jit/pallas body",
        "XTB102": "explicit host transfer (.item()/.tolist()/device_get) "
                  "inside a jit/pallas body",
        "XTB103": "numpy call on traced operands inside a jit/pallas body "
                  "(numpy executes on host and forces a sync)",
    }

    # ------------------------------------------------------------ discovery
    def _traced_functions(self, tree: ast.AST) -> List[tuple]:
        """[(function node, static param names)]"""
        traced: List[tuple] = []
        traced_names: Set[str] = set()
        funcs_by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs_by_name.setdefault(node.name, []).append(node)
                for dec in node.decorator_list:
                    if _is_jit_expr(dec):
                        static = (_static_params_from_jit(dec, node)
                                  if isinstance(dec, ast.Call) else set())
                        traced.append((node, static))
                        break
                    if isinstance(dec, ast.Call) and _is_jit_expr(dec.func):
                        traced.append(
                            (node, _static_params_from_jit(dec, node)))
                        break
            elif isinstance(node, ast.Call) and _is_tracing_call(node):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Lambda):
                        traced.append((sub, set()))
                    elif isinstance(sub, ast.Name) and sub is not node.func:
                        traced_names.add(sub.id)
        for name in traced_names:
            for fn in funcs_by_name.get(name, ()):
                traced.append((fn, set()))
        seen: Set[int] = set()
        out = []
        for fn, static in traced:
            if id(fn) not in seen:
                seen.add(id(fn))
                out.append((fn, static))
        return out

    # ------------------------------------------------------------- checking
    def check_file(self, sf: SourceFile, project: Project,
                   ) -> Iterable[Finding]:
        findings: List[Finding] = []
        for fn, static_params in self._traced_functions(sf.tree):
            env = _StaticEnv(fn, static_params)
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    tail = _attr_tail(node.func)
                    if (isinstance(node.func, ast.Name)
                            and tail in _HOST_SYNC_BUILTINS
                            and node.args
                            and not env.is_static(node.args[0])):
                        findings.append(sf.finding(
                            node, "XTB101",
                            f"{tail}() on a possibly-traced value inside "
                            f"a traced body (forces a host sync; hoist it "
                            f"out of the jit or use jnp)"))
                    elif (isinstance(node.func, ast.Attribute)
                          and tail in _HOST_PULL_METHODS):
                        findings.append(sf.finding(
                            node, "XTB102",
                            f".{tail}() inside a traced body transfers to "
                            f"host per trace — move it outside the jit "
                            f"boundary"))
                    elif tail == "device_get":
                        findings.append(sf.finding(
                            node, "XTB102",
                            "jax.device_get inside a traced body — move "
                            "the transfer outside the jit boundary"))
                    elif (_is_numpy_call(node.func)
                          and not all(env.is_static(a) for a in node.args)):
                        findings.append(sf.finding(
                            node, "XTB103",
                            f"numpy call ({ast.unparse(node.func)}) on "
                            f"traced operands inside a traced body — "
                            f"numpy runs on host; use jnp or hoist to "
                            f"the caller"))
        return findings
