"""XTB9xx — concurrency contract: static lock-order analysis.

XTB201 checks *that* guarded attributes are locked; this family checks
*how* locks compose.  It discovers every lock the package creates
(``threading.Lock``/``RLock``/``Condition`` attributes, module-level
locks, ``fcntl.flock`` sites), rebuilds the may-acquire-after graph from
``with`` blocks and explicit ``acquire``/``release`` across the
intra-class and cross-module call graph, and reports:

- **XTB901** — lock-order inversion: a cycle in the may-acquire-after
  graph.  Two threads taking the same pair of locks in opposite orders
  is the classic ABBA deadlock; the finding prints a witness site for
  every edge on the cycle so both paths are visible in the report.
- **XTB902** — blocking call while holding a lock: a socket/wire
  send or recv, ``Future.result``, queue ``get``, ``subprocess``,
  ``time.sleep``, ``fcntl.flock``, or a ``faults.maybe_inject`` seam
  reached inside a lock scope.  One wedged peer then stalls every
  thread that wants the lock — the hang class PR 14's watchdog mops up
  at runtime becomes a lint failure instead.
- **XTB903** — unbounded lock acquisition inside a ``signal``/
  ``atexit``/fork handler.  Interpreter shutdown and ``fork()`` run
  these on a thread that may not own the lock; a plain ``with lock:``
  there can hang exit (or deadlock the forked child) forever.  Bounded
  acquires (``acquire(timeout=...)``/``acquire(blocking=False)``) are
  the sanctioned shape, as is the paired fork-safety idiom
  (``os.register_at_fork(before=l.acquire, after_in_parent=l.release,
  after_in_child=<releaser>)``).

Two *structural* escape hatches exist instead of comment suppressions
(the gate forbids blanket disables, and these keep the contract visible
in code):

- A **pure serialization lock** — one whose every ``with`` body in the
  whole package is a single simple statement — exempts that single
  statement from XTB902.  This is the tx-lock idiom: the lock exists
  only to serialize one wire write; there is no other critical section
  it could stall.
- A module may declare ``_XTB_SERIAL_LOCKS = ("Class.attr", ...)`` to
  mark a lock as an intentional collective-serialization lock (held
  across a blocking protocol round by design, with an out-of-band
  interrupt path).  Declared locks are exempt from XTB902 but still
  participate in XTB901 ordering — declaring a lock never hides a
  deadlock cycle.

``Condition(self._lock)`` aliases the condition attribute to the lock it
wraps (one underlying lock, one graph node), and ``.wait()`` on a held
lock/condition is never a blocking finding for *that* lock (wait
releases it) — only for other locks still held around it.

See docs/static_analysis.md (XTB9xx section) and the runtime half in
``reliability/lockdep.py``.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, \
    Tuple

from .blocking import _call_tail, _has_kwarg, _queueish, _receiver_tail
from .core import Finding, Project, Rule, SourceFile

SERIAL_DECL = "_XTB_SERIAL_LOCKS"

_LOCK_FACTORIES = ("Lock", "RLock", "Condition")

# wire-protocol helpers in this package that stall on a peer
_WIRE_TAILS = ("send_msg", "recv_msg", "send_frame", "recv_frame",
               "_recv_exact")
# socket-level tails that stall on the network regardless of receiver
_SOCKET_TAILS = ("accept", "connect", "recv", "recv_into", "sendall",
                 "create_connection", "getaddrinfo")
_SUBPROCESS_TAILS = ("run", "check_call", "check_output", "Popen")

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _is_lock_ctor(node: ast.expr) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' when ``node`` constructs one."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _LOCK_FACTORIES and \
            isinstance(f.value, ast.Name) and f.value.id == "threading":
        return f.attr
    if isinstance(f, ast.Name) and f.id in _LOCK_FACTORIES:
        return f.id
    return None


def _self_attr(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _single_simple(body: Sequence[ast.stmt]) -> bool:
    """True when a with-body is one simple (non-compound) statement —
    the serialization-lock shape."""
    return len(body) == 1 and isinstance(
        body[0], (ast.Expr, ast.Assign, ast.AnnAssign, ast.AugAssign,
                  ast.Return, ast.Pass))


def _fn(key: str) -> str:
    return key.split("::", 1)[1] if "::" in key else key


def _bounded_acquire(node: ast.Call) -> bool:
    if _has_kwarg(node, "timeout"):
        return True
    for kw in node.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    if node.args and isinstance(node.args[0], ast.Constant) and \
            node.args[0].value is False:
        return True
    return False


def _flock_nonblocking(node: ast.Call) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "LOCK_NB":
            return True
        if isinstance(sub, ast.Name) and sub.id == "LOCK_NB":
            return True
    return False


class _ClassInfo:
    def __init__(self, name: str, rel: str) -> None:
        self.name = name
        self.rel = rel
        self.lock_attrs: Dict[str, str] = {}   # attr -> canonical attr
        self.attr_types: Dict[str, str] = {}   # attr -> class name
        self.methods: Set[str] = set()


class _ModuleInfo:
    def __init__(self, sf: SourceFile) -> None:
        self.sf = sf
        self.rel = sf.rel
        self.locks: Set[str] = set()           # module-level lock names
        self.classes: Dict[str, _ClassInfo] = {}
        self.funcs: Set[str] = set()           # module-level function names
        self.import_mods: Dict[str, str] = {}  # alias -> module basename
        self.import_names: Dict[str, Tuple[str, str]] = {}  # name->(mod,orig)
        self.serial_decls: List[str] = []


class _Held:
    __slots__ = ("lock", "serial")

    def __init__(self, lock: str, serial: bool) -> None:
        self.lock = lock
        self.serial = serial


class _Edge:
    __slots__ = ("src", "dst", "sf", "node", "desc")

    def __init__(self, src: str, dst: str, sf: SourceFile, node: ast.AST,
                 desc: str) -> None:
        self.src = src
        self.dst = dst
        self.sf = sf
        self.node = node
        self.desc = desc


class _Analysis:
    """Whole-project lock model, built in finalize."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.modules: Dict[str, _ModuleInfo] = {}
        self.class_by_name: Dict[str, _ClassInfo] = {}
        self.lock_attr_owners: Dict[str, Set[str]] = {}
        self.mod_by_base: Dict[str, Optional[str]] = {}
        self.serial_locks: Set[str] = set()
        # per-function facts (key: "<rel>::<qualname>")
        self.direct_acq: Dict[str, List[Tuple[str, SourceFile, ast.AST]]] = {}
        self.calls: Dict[str, List[Tuple[str, Tuple[str, ...], ast.AST]]] = {}
        self.edges: List[_Edge] = []
        self.blocking: List[Tuple[SourceFile, ast.AST, str,
                                  Tuple[_Held, ...]]] = []
        # (sf, registration node, kind, ("func", key) | ("lock", lock id))
        self.handlers: List[Tuple[SourceFile, ast.AST, str,
                                  Tuple[str, str]]] = []
        # locks ever held via a multi-statement with / explicit acquire —
        # the complement of the pure-serialization set
        self.multi_stmt_locks: Set[str] = set()

    # ---------------- discovery ----------------

    def discover(self) -> None:
        for sf in self.project.files:
            mi = _ModuleInfo(sf)
            self.modules[mi.rel] = mi
            base = mi.rel.rsplit("/", 1)[-1]
            base = base[:-3] if base.endswith(".py") else base
            if base in self.mod_by_base:       # ambiguous basename: disable
                self.mod_by_base[base] = None
            else:
                self.mod_by_base[base] = mi.rel
            for node in sf.tree.body:
                self._discover_top(mi, node)
        for mi in self.modules.values():
            for ci in mi.classes.values():
                if ci.name not in self.class_by_name:
                    self.class_by_name[ci.name] = ci
                for attr in ci.lock_attrs:
                    self.lock_attr_owners.setdefault(attr, set()).add(ci.name)
        for mi in self.modules.values():
            for decl in mi.serial_decls:
                lid = self._declared_lock_id(mi, decl)
                if lid:
                    self.serial_locks.add(lid)

    def _discover_top(self, mi: _ModuleInfo, node: ast.stmt) -> None:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if _is_lock_ctor(node.value):
                mi.locks.add(name)
            elif name == SERIAL_DECL and isinstance(
                    node.value, (ast.Tuple, ast.List)):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, str):
                        mi.serial_decls.append(elt.value)
        elif isinstance(node, _FUNC_DEFS):
            mi.funcs.add(node.name)
        elif isinstance(node, ast.ClassDef):
            ci = _ClassInfo(node.name, mi.rel)
            mi.classes[node.name] = ci
            self._discover_class(ci, node)
        elif isinstance(node, ast.Import):
            for a in node.names:
                mi.import_mods[a.asname or a.name.split(".")[-1]] = \
                    a.name.split(".")[-1]
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                mi.import_mods[a.asname or a.name] = a.name
                mi.import_names[a.asname or a.name] = (
                    (node.module or "").split(".")[-1], a.name)
        elif isinstance(node, (ast.If, ast.Try)):
            # guarded module top (if hasattr(os, ...):, try: import ...)
            for sub in getattr(node, "body", ()):
                self._discover_top(mi, sub)
            for sub in getattr(node, "orelse", ()):
                self._discover_top(mi, sub)

    def _discover_class(self, ci: _ClassInfo, cls: ast.ClassDef) -> None:
        aliases: Dict[str, str] = {}
        for meth in cls.body:
            if not isinstance(meth, _FUNC_DEFS):
                continue
            ci.methods.add(meth.name)
            for node in ast.walk(meth):
                if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                    continue
                attr = _self_attr(node.targets[0])
                if attr is None:
                    continue
                kind = _is_lock_ctor(node.value)
                if kind:
                    ci.lock_attrs.setdefault(attr, attr)
                    if kind == "Condition" and node.value.args:
                        wrapped = _self_attr(node.value.args[0])
                        if wrapped is not None:
                            aliases[attr] = wrapped
                elif isinstance(node.value, ast.Call) and \
                        isinstance(node.value.func, ast.Name):
                    ci.attr_types.setdefault(attr, node.value.func.id)
        # Condition(self._x) shares _x's underlying lock: one graph node
        for cond_attr, wrapped in aliases.items():
            if wrapped in ci.lock_attrs:
                ci.lock_attrs[cond_attr] = ci.lock_attrs[wrapped]

    def _declared_lock_id(self, mi: _ModuleInfo, decl: str) -> Optional[str]:
        if "." in decl:
            cls, attr = decl.split(".", 1)
            ci = mi.classes.get(cls) or self.class_by_name.get(cls)
            if ci is not None and attr in ci.lock_attrs:
                return f"{ci.name}.{ci.lock_attrs[attr]}"
            return f"{cls}.{attr}"
        if decl in mi.locks:
            return f"{mi.rel}:{decl}"
        return None

    # ---------------- resolution ----------------

    def _resolve_lock(self, mi: _ModuleInfo, ci: Optional[_ClassInfo],
                      expr: ast.expr) -> Optional[str]:
        """Lock identity for an expression, or None when untracked."""
        if isinstance(expr, ast.Name):
            if expr.id in mi.locks:
                return f"{mi.rel}:{expr.id}"
            imp = mi.import_names.get(expr.id)
            if imp is not None:
                rel = self.mod_by_base.get(imp[0])
                if rel and imp[1] in self.modules[rel].locks:
                    return f"{rel}:{imp[1]}"
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        attr = expr.attr
        recv = expr.value
        if isinstance(recv, ast.Name) and recv.id == "self":
            if ci is not None and attr in ci.lock_attrs:
                return f"{ci.name}.{ci.lock_attrs[attr]}"
            return None
        if isinstance(recv, ast.Name) and recv.id in mi.import_mods:
            base = mi.import_mods[recv.id]
            rel = self.mod_by_base.get(base)
            if rel and attr in self.modules[rel].locks:
                return f"{rel}:{attr}"
            return None
        # self.<typed attr>.<lock attr>
        inner = _self_attr(recv)
        if inner is not None and ci is not None:
            tname = ci.attr_types.get(inner)
            tci = self.class_by_name.get(tname) if tname else None
            if tci is not None and attr in tci.lock_attrs:
                return f"{tci.name}.{tci.lock_attrs[attr]}"
        # fallback: a lock-attribute name unique to one class (rep.txlock)
        owners = self.lock_attr_owners.get(attr)
        if owners is not None and len(owners) == 1:
            tci = self.class_by_name[next(iter(owners))]
            return f"{tci.name}.{tci.lock_attrs[attr]}"
        return None

    def _resolve_callee(self, mi: _ModuleInfo, ci: Optional[_ClassInfo],
                        func: ast.expr) -> Optional[str]:
        """Function key for a call target within the project, or None."""
        if isinstance(func, ast.Name):
            if func.id in mi.funcs:
                return f"{mi.rel}::{func.id}"
            imp = mi.import_names.get(func.id)
            if imp is not None:
                rel = self.mod_by_base.get(imp[0])
                if rel and imp[1] in self.modules[rel].funcs:
                    return f"{rel}::{imp[1]}"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        meth = func.attr
        recv = func.value
        if isinstance(recv, ast.Name) and recv.id == "self" and ci is not None:
            if meth in ci.methods:
                return f"{ci.rel}::{ci.name}.{meth}"
            return None
        if isinstance(recv, ast.Name) and recv.id in mi.import_mods:
            rel = self.mod_by_base.get(mi.import_mods[recv.id])
            if rel is not None and meth in self.modules[rel].funcs:
                return f"{rel}::{meth}"
            return None
        inner = _self_attr(recv)
        if inner is not None and ci is not None:
            tci = self.class_by_name.get(ci.attr_types.get(inner, ""))
            if tci is not None and meth in tci.methods:
                return f"{tci.rel}::{tci.name}.{meth}"
        return None

    # ---------------- per-function scan ----------------

    def scan(self) -> None:
        for rel in sorted(self.modules):
            mi = self.modules[rel]
            for node in mi.sf.tree.body:
                self._scan_module_stmt(mi, node)

    def _scan_module_stmt(self, mi: _ModuleInfo, node: ast.stmt) -> None:
        if isinstance(node, _FUNC_DEFS):
            self._scan_func(mi, None, node, node.name)
        elif isinstance(node, ast.ClassDef):
            ci = mi.classes[node.name]
            for meth in node.body:
                if isinstance(meth, _FUNC_DEFS):
                    self._scan_func(mi, ci, meth,
                                    f"{node.name}.{meth.name}")
        elif isinstance(node, (ast.If, ast.Try)):
            for sub in getattr(node, "body", ()):
                self._scan_module_stmt(mi, sub)
            for sub in getattr(node, "orelse", ()):
                self._scan_module_stmt(mi, sub)
        else:
            self._scan_stmt(mi, None, f"{mi.rel}::<module>", node, [], [])

    def _scan_func(self, mi: _ModuleInfo, ci: Optional[_ClassInfo],
                   node: ast.AST, qual: str) -> None:
        key = f"{mi.rel}::{qual}"
        self.direct_acq.setdefault(key, [])
        self.calls.setdefault(key, [])
        for dec in getattr(node, "decorator_list", ()):
            if isinstance(dec, ast.Attribute) and dec.attr == "register" \
                    and isinstance(dec.value, ast.Name) \
                    and dec.value.id == "atexit":
                self.handlers.append((mi.sf, node, "atexit", ("func", key)))
        body = [node.body] if isinstance(node, ast.Lambda) else node.body
        explicit: List[_Held] = []
        for stmt in body:
            self._scan_stmt(mi, ci, key, stmt, [], explicit)

    def _record_acquire(self, mi: _ModuleInfo, key: str, lock: str,
                        node: ast.AST, held: Sequence[_Held]) -> None:
        self.direct_acq.setdefault(key, []).append((lock, mi.sf, node))
        for h in held:
            if h.lock != lock:
                self.edges.append(_Edge(
                    h.lock, lock, mi.sf, node,
                    f"{_fn(key)} ({mi.rel}:{getattr(node, 'lineno', 0)}) "
                    f"acquires {lock} while holding {h.lock}"))

    def _scan_stmt(self, mi: _ModuleInfo, ci: Optional[_ClassInfo], key: str,
                   stmt: ast.AST, held: List[_Held],
                   explicit: List[_Held]) -> None:
        if isinstance(stmt, _FUNC_DEFS):
            # closure: runs later on some other stack — no lock credit
            self._scan_func(mi, ci, stmt,
                            f"{_fn(key)}.<locals>.{stmt.name}")
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            entered: List[_Held] = []
            serial = _single_simple(stmt.body)
            for item in stmt.items:
                self._scan_expr(mi, ci, key, item.context_expr, held,
                                explicit)
                lock = self._resolve_lock(mi, ci, item.context_expr)
                if lock is not None:
                    self._record_acquire(mi, key, lock, stmt, held + explicit)
                    entered.append(_Held(lock, serial))
                    if not serial:
                        self.multi_stmt_locks.add(lock)
            for inner in stmt.body:
                self._scan_stmt(mi, ci, key, inner, held + entered, explicit)
            return
        if isinstance(stmt, ast.Try):
            for part in (stmt.body, stmt.handlers, stmt.orelse,
                         stmt.finalbody):
                for inner in part:
                    if isinstance(inner, ast.ExceptHandler):
                        for s in inner.body:
                            self._scan_stmt(mi, ci, key, s, held, explicit)
                    else:
                        self._scan_stmt(mi, ci, key, inner, held, explicit)
            return
        if isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While)):
            for field in ("test", "iter"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, ast.expr):
                    self._scan_expr(mi, ci, key, sub, held, explicit)
            for inner in list(stmt.body) + list(stmt.orelse):
                self._scan_stmt(mi, ci, key, inner, held, explicit)
            return
        self._scan_expr(mi, ci, key, stmt, held, explicit)

    def _scan_expr(self, mi: _ModuleInfo, ci: Optional[_ClassInfo], key: str,
                   root: ast.AST, held: List[_Held],
                   explicit: List[_Held]) -> None:
        """Pruned walk: calls are checked with the current held set;
        lambdas/defs are scanned as closures with an empty one."""
        stack: List[ast.AST] = [root]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                self._scan_func(mi, ci, node,
                                f"{_fn(key)}.<locals>.<lambda:{node.lineno}>")
                continue
            if isinstance(node, _FUNC_DEFS):
                self._scan_func(mi, ci, node,
                                f"{_fn(key)}.<locals>.{node.name}")
                continue
            if isinstance(node, ast.Call):
                self._scan_call(mi, ci, key, node, held, explicit)
            stack.extend(ast.iter_child_nodes(node))

    def _scan_call(self, mi: _ModuleInfo, ci: Optional[_ClassInfo], key: str,
                   node: ast.Call, held: List[_Held],
                   explicit: List[_Held]) -> None:
        tail = _call_tail(node.func)
        eff = held + explicit
        # --- explicit acquire/release on a tracked lock ---
        if tail in ("acquire", "release") and \
                isinstance(node.func, ast.Attribute):
            lock = self._resolve_lock(mi, ci, node.func.value)
            if lock is not None:
                if tail == "acquire":
                    if not _bounded_acquire(node):
                        self._record_acquire(mi, key, lock, node, eff)
                    explicit.append(_Held(lock, False))
                    self.multi_stmt_locks.add(lock)
                else:
                    for i in range(len(explicit) - 1, -1, -1):
                        if explicit[i].lock == lock:
                            del explicit[i]
                            break
                return
        # --- handler registrations (XTB903) ---
        self._scan_registration(mi, ci, key, node, tail)
        # --- wait: releases the lock it is called on ---
        if tail in ("wait", "wait_for") and \
                isinstance(node.func, ast.Attribute):
            target = self._resolve_lock(mi, ci, node.func.value)
            if target is not None and any(h.lock == target for h in eff):
                rest = tuple(h for h in eff if h.lock != target)
                if rest:
                    self.blocking.append((mi.sf, node, f".{tail}()", rest))
            elif eff and not node.args and not _has_kwarg(node, "timeout"):
                self.blocking.append((mi.sf, node,
                                      f"unbounded .{tail}()", tuple(eff)))
            return
        # --- blocking tokens (XTB902) ---
        token = self._blocking_token(node, tail)
        if token is not None and eff:
            self.blocking.append((mi.sf, node, token, tuple(eff)))
        # --- call graph ---
        callee = self._resolve_callee(mi, ci, node.func)
        if callee is not None:
            self.calls.setdefault(key, []).append(
                (callee, tuple(h.lock for h in eff), node))

    def _blocking_token(self, node: ast.Call, tail: str) -> Optional[str]:
        recv = _receiver_tail(node.func)
        if tail == "sleep" and recv in ("", "time"):
            return "time.sleep"
        if tail in _WIRE_TAILS:
            return f"{tail}()"
        if tail == "maybe_inject":
            return "maybe_inject() fault seam"
        if tail in _SUBPROCESS_TAILS and recv == "subprocess":
            return f"subprocess.{tail}"
        if tail == "communicate":
            return ".communicate()"
        if tail in _SOCKET_TAILS and isinstance(node.func, ast.Attribute):
            return f".{tail}()"
        if tail == "result" and isinstance(node.func, ast.Attribute):
            return ".result()"
        if tail == "get" and _queueish(recv):
            return "queue .get()"
        if tail == "join" and isinstance(node.func, ast.Attribute) \
                and not node.args and not node.keywords:
            return ".join()"
        if tail in ("flock", "lockf") and not _flock_nonblocking(node):
            return f"fcntl.{tail}"
        return None

    # ---------------- handler registrations ----------------

    def _scan_registration(self, mi: _ModuleInfo, ci: Optional[_ClassInfo],
                           key: str, node: ast.Call, tail: str) -> None:
        recv = _receiver_tail(node.func)
        if tail == "register" and recv == "atexit" and node.args:
            self._record_handler(mi, ci, key, node, "atexit", node.args[0])
        elif tail == "signal" and recv == "signal" and len(node.args) >= 2:
            self._record_handler(mi, ci, key, node, "signal", node.args[1])
        elif tail == "register_at_fork":
            self._scan_at_fork(mi, ci, key, node)

    def _scan_at_fork(self, mi: _ModuleInfo, ci: Optional[_ClassInfo],
                      key: str, node: ast.Call) -> None:
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        before, in_parent = kw.get("before"), kw.get("after_in_parent")
        in_child = kw.get("after_in_child")
        # the sanctioned fork-safety idiom: hold L across fork, release
        # on both sides — before=L.acquire, after_in_parent=L.release,
        # after_in_child releasing the same lock
        block = self._bound_lock_method(mi, ci, before, "acquire")
        if block is not None and \
                self._bound_lock_method(mi, ci, in_parent,
                                        "release") == block and \
                self._releases(mi, ci, in_child, block):
            return
        for tag, h in (("fork-before", before), ("fork-parent", in_parent),
                       ("fork-child", in_child)):
            if h is not None:
                self._record_handler(mi, ci, key, node, tag, h)

    def _bound_lock_method(self, mi: _ModuleInfo, ci: Optional[_ClassInfo],
                           expr: Optional[ast.expr], meth: str,
                           ) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and expr.attr == meth:
            return self._resolve_lock(mi, ci, expr.value)
        return None

    def _releases(self, mi: _ModuleInfo, ci: Optional[_ClassInfo],
                  expr: Optional[ast.expr], lock: str) -> bool:
        if self._bound_lock_method(mi, ci, expr, "release") == lock:
            return True
        if isinstance(expr, ast.Name):
            fn = self._find_func_node(mi, expr.id)
            if fn is not None:
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Call) and \
                            _call_tail(sub.func) == "release" and \
                            isinstance(sub.func, ast.Attribute) and \
                            self._resolve_lock(mi, ci,
                                               sub.func.value) == lock:
                        return True
        return False

    def _find_func_node(self, mi: _ModuleInfo, name: str,
                        ) -> Optional[ast.AST]:
        for node in ast.walk(mi.sf.tree):
            if isinstance(node, _FUNC_DEFS) and node.name == name:
                return node
        return None

    def _record_handler(self, mi: _ModuleInfo, ci: Optional[_ClassInfo],
                        key: str, reg_node: ast.AST, kind: str,
                        expr: ast.expr) -> None:
        lock = self._bound_lock_method(mi, ci, expr, "acquire")
        if lock is not None:
            self.handlers.append((mi.sf, reg_node, kind, ("lock", lock)))
            return
        if self._bound_lock_method(mi, ci, expr, "release") is not None:
            return  # a bare release never blocks
        fkey: Optional[str] = None
        if isinstance(expr, ast.Name):
            if expr.id in mi.funcs:
                fkey = f"{mi.rel}::{expr.id}"
        elif isinstance(expr, ast.Lambda):
            fkey = f"{mi.rel}::{_fn(key)}.<locals>.<lambda:{expr.lineno}>"
        elif isinstance(expr, ast.Attribute):
            inner = _self_attr(expr)
            if inner is not None and ci is not None and inner in ci.methods:
                fkey = f"{ci.rel}::{ci.name}.{inner}"
        if fkey is not None:
            self.handlers.append((mi.sf, reg_node, kind, ("func", fkey)))

    # ---------------- fixpoint + verdicts ----------------

    def fixpoint(self) -> Dict[str, Set[str]]:
        """Transitive may-acquire set per function (unbounded acquires)."""
        trans: Dict[str, Set[str]] = {
            k: {lock for lock, _, _ in v}
            for k, v in self.direct_acq.items()}
        changed = True
        rounds = 0
        while changed and rounds < 64:
            changed = False
            rounds += 1
            for k, calls in self.calls.items():
                cur = trans.setdefault(k, set())
                before = len(cur)
                for callee, _, _ in calls:
                    cur |= trans.get(callee, set())
                if len(cur) != before:
                    changed = True
        return trans

    def call_edges(self, trans: Dict[str, Set[str]]) -> None:
        """Project callee acquisition sets onto held-at-callsite locks."""
        for k in sorted(self.calls):
            for callee, held_ids, node in self.calls[k]:
                if not held_ids:
                    continue
                mi = self.modules[k.split("::", 1)[0]]
                for dst in sorted(trans.get(callee, ())):
                    if dst in held_ids:
                        continue  # reentrant hold along the call chain
                    for src in held_ids:
                        self.edges.append(_Edge(
                            src, dst, mi.sf, node,
                            f"{_fn(k)} ({mi.rel}:"
                            f"{getattr(node, 'lineno', 0)}) holds {src} "
                            f"while calling {_fn(callee)} which acquires "
                            f"{dst}"))


class LockOrderRule(Rule):
    name = "lock-order"
    codes = {
        "XTB901": "lock-order inversion: a cycle in the may-acquire-after "
                  "graph (ABBA deadlock); both witness paths printed",
        "XTB902": "blocking call (wire/socket/queue/subprocess/sleep/"
                  "fault-seam) while holding a lock",
        "XTB903": "unbounded lock acquisition in a signal/atexit/fork "
                  "handler (shutdown or forked child can hang)",
    }

    def finalize(self, project: Project) -> Iterable[Finding]:
        an = _Analysis(project)
        an.discover()
        an.scan()
        trans = an.fixpoint()
        an.call_edges(trans)
        findings: List[Finding] = []
        findings.extend(self._cycles(an))
        findings.extend(self._blocking(an))
        findings.extend(self._handlers(an, trans))
        return findings

    # --- XTB901 ---

    def _cycles(self, an: _Analysis) -> Iterator[Finding]:
        adj: Dict[str, Dict[str, _Edge]] = {}
        for e in an.edges:
            adj.setdefault(e.src, {}).setdefault(e.dst, e)
        for scc in _sccs(adj):
            if len(scc) < 2:
                continue
            cycle = _cycle_path(adj, scc)
            if not cycle:
                continue
            first = cycle[0]
            path = " -> ".join([e.src for e in cycle] + [cycle[0].src])
            witnesses = "; ".join(
                f"path {i + 1}: {e.desc}" for i, e in enumerate(cycle))
            yield first.sf.finding(
                first.node, "XTB901",
                f"lock-order inversion {path} — two threads taking these "
                f"locks in opposite orders deadlock ({witnesses}); pick one "
                f"order and document it in docs/reliability.md's lock "
                f"hierarchy")

    # --- XTB902 ---

    def _blocking(self, an: _Analysis) -> Iterator[Finding]:
        for sf, node, token, helds in an.blocking:
            locks = []
            for h in helds:
                if h.serial and h.lock not in an.multi_stmt_locks:
                    continue  # pure serialization lock, sole statement
                if h.lock in an.serial_locks:
                    continue  # declared intentional serialization lock
                locks.append(h.lock)
            if not locks:
                continue
            held = ", ".join(dict.fromkeys(locks))
            yield sf.finding(
                node, "XTB902",
                f"{token} while holding {held}: one wedged peer stalls "
                f"every thread wanting the lock — collect under the lock, "
                f"do the blocking work after release (or declare a "
                f"serialization lock via {SERIAL_DECL})")

    # --- XTB903 ---

    def _handlers(self, an: _Analysis,
                  trans: Dict[str, Set[str]]) -> Iterator[Finding]:
        for sf, node, kind, target in an.handlers:
            if target[0] == "lock":
                locks: List[str] = [target[1]]
            else:
                locks = sorted(trans.get(target[1], ()))
            if not locks:
                continue
            what = target[1] if target[0] == "func" else \
                f"{target[1]}.acquire"
            yield sf.finding(
                node, "XTB903",
                f"{kind} handler {_fn(what)} acquires "
                f"{', '.join(locks)} unbounded — shutdown/fork runs on a "
                f"thread that may not own it and hangs forever; use "
                f"acquire(timeout=...) and degrade, or the paired "
                f"register_at_fork acquire/release idiom")


def _sccs(adj: Dict[str, Dict[str, _Edge]]) -> List[List[str]]:
    """Tarjan (iterative), deterministic node order."""
    nodes = sorted(set(adj) | {d for m in adj.values() for d in m})
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in nodes:
        if root in index:
            continue
        work: List[Tuple[str, Iterator[str]]] = [
            (root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                out.append(sorted(comp))
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
    return out


def _cycle_path(adj: Dict[str, Dict[str, _Edge]],
                scc: List[str]) -> List[_Edge]:
    """A closed edge walk through an SCC starting at its smallest node."""
    start = scc[0]
    members = set(scc)
    # BFS back to start staying inside the SCC
    best: Dict[str, List[_Edge]] = {start: []}
    frontier = [start]
    while frontier:
        nxt: List[str] = []
        for v in frontier:
            for w in sorted(adj.get(v, ())):
                if w not in members:
                    continue
                if w == start and best[v]:
                    return best[v] + [adj[v][w]]
                if w != start and w not in best:
                    best[w] = best[v] + [adj[v][w]]
                    nxt.append(w)
        frontier = nxt
    # two-node cycle where the first hop closes immediately
    for w in sorted(adj.get(start, ())):
        if w in members and start in adj.get(w, {}):
            return [adj[start][w], adj[w][start]]
    return []
