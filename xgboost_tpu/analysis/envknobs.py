"""XTB9xx (knobs) — ``XGBOOST_TPU_*``/``XTB_*`` env-knob catalog.

The package grew ~40 environment knobs across telemetry, reliability,
serving, and training; operators discover them by grepping.  This rule
is the XTB4xx metric-catalog contract applied to configuration: every
env read must appear in the ``docs/knobs.md`` table, and every table row
must still correspond to a live read — so the doc IS the catalog and
cannot rot in either direction.

- **XTB905** — an ``XGBOOST_TPU_*``/``XTB_*`` env variable read in the
  package (``os.environ.get``/``[]``/``setdefault``/``pop``,
  ``os.getenv``) that the ``docs/knobs.md`` table does not mention.
- **XTB906** — a knob named in the ``docs/knobs.md`` table that nothing
  in the package reads (renamed or deleted knob leaving a stale row).
  Pattern rows — names containing ``<`` (e.g. the per-seam
  ``XGBOOST_TPU_WATCHDOG_<SEAM>_S`` family built dynamically) — are
  exempt: the dynamic construction is invisible to a static read scan.

Knob names usually flow through module constants (``ENV_HZ =
"XGBOOST_TPU_PROF_HZ"`` ... ``os.environ.get(ENV_HZ)``), often imported
across modules; the rule resolves a constant reference project-wide when
the bare name or attribute maps to exactly one knob-shaped value.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Tuple

from .core import Finding, Project, Rule, SourceFile

_FACT_READS = "envknobs.reads"      # list[(name-or-None, ref, path, line)]
_FACT_CONSTS = "envknobs.consts"    # const name -> set of knob values

_PREFIXES = ("XGBOOST_TPU_", "XTB_")
_DOC = "knobs.md"
_DOC_TOKEN_RE = re.compile(r"\b(?:XGBOOST_TPU|XTB)_[A-Z0-9_]*(?:<[A-Z_]+>"
                           r"[A-Z0-9_]*)?\b")
_READ_METHODS = ("get", "setdefault", "pop", "getenv")


def _knobbish(value: object) -> bool:
    return isinstance(value, str) and value.startswith(_PREFIXES)


def _const_str(node: ast.AST, local: Dict[str, str]) -> Optional[str]:
    """Fold a module-level string expression: literals, references to
    already-seen knob consts, and ``+`` concatenations of those (the
    ``_OWNER_VAR = ENV_VAR + "_OWNER_PID"`` derived-knob idiom)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return local.get(node.id)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _const_str(node.left, local)
        right = _const_str(node.right, local)
        if left is not None and right is not None:
            return left + right
    return None


def _env_read_arg(node: ast.AST) -> Optional[ast.expr]:
    """The name expression when ``node`` reads the environment."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        tail = node.func.attr
        recv = node.func.value
        if tail == "getenv" and isinstance(recv, ast.Name) \
                and recv.id == "os" and node.args:
            return node.args[0]
        if tail in _READ_METHODS and isinstance(recv, ast.Attribute) \
                and recv.attr == "environ" and node.args:
            return node.args[0]
    if isinstance(node, ast.Subscript) and \
            isinstance(node.value, ast.Attribute) and \
            node.value.attr == "environ":
        sl = node.slice
        return sl if isinstance(sl, ast.expr) else None
    return None


class EnvKnobRule(Rule):
    name = "env-knobs"
    codes = {
        "XTB905": "XGBOOST_TPU_*/XTB_* env read missing from the "
                  "docs/knobs.md catalog table",
        "XTB906": "knob named in docs/knobs.md that nothing reads "
                  "(stale row; pattern rows with <...> are exempt)",
    }

    def check_file(self, sf: SourceFile, project: Project,
                   ) -> Iterable[Finding]:
        reads: list = project.facts.setdefault(_FACT_READS, [])
        consts: Dict[str, set] = project.facts.setdefault(_FACT_CONSTS, {})
        local: Dict[str, str] = {}
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                value = _const_str(node.value, local)
                if value is not None and _knobbish(value):
                    local[node.targets[0].id] = value
                    consts.setdefault(node.targets[0].id,
                                      set()).add(value)
        for node in ast.walk(sf.tree):
            arg = _env_read_arg(node)
            if arg is None:
                continue
            line = getattr(node, "lineno", 1)
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if arg.value.startswith(_PREFIXES):
                    reads.append((arg.value, None, sf.path, line))
            elif isinstance(arg, ast.Name):
                if arg.id in local:
                    reads.append((local[arg.id], None, sf.path, line))
                else:
                    reads.append((None, arg.id, sf.path, line))
            elif isinstance(arg, ast.Attribute):
                reads.append((None, arg.attr, sf.path, line))
        return ()

    def finalize(self, project: Project) -> Iterable[Finding]:
        doc = project.doc_text(_DOC)
        if doc is None:
            return ()
        raw: List[Tuple[Optional[str], Optional[str], str, int]] = (
            project.facts.get(_FACT_READS) or [])
        consts: Dict[str, set] = project.facts.get(_FACT_CONSTS) or {}
        read_names: Dict[str, Tuple[str, int]] = {}
        for name, ref, path, line in raw:
            if name is None and ref is not None:
                vals = consts.get(ref, ())
                if len(vals) == 1:
                    name = next(iter(vals))
            if name is not None:
                read_names.setdefault(name, (path, line))
        findings: List[Finding] = []
        for name in sorted(read_names):
            if name not in doc:
                path, line = read_names[name]
                findings.append(Finding(
                    path, line, 0, "XTB905",
                    f"env knob {name!r} read here but missing from "
                    f"{project.doc_path(_DOC)} — add a row (name, default, "
                    f"consumer, effect) to the knobs table"))
        for i, line_text in enumerate(doc.splitlines(), start=1):
            for token in _DOC_TOKEN_RE.findall(line_text):
                if "<" in token:
                    continue  # dynamic per-seam/per-site pattern row
                if token in _PREFIXES or token in ("XGBOOST_TPU_",):
                    continue
                if token not in read_names:
                    findings.append(Finding(
                        project.doc_path(_DOC), i, 0, "XTB906",
                        f"knob {token!r} documented but nothing in the "
                        f"package reads it — stale row (renamed knob?) or "
                        f"missing consumer"))
        return findings
