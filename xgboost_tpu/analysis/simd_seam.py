"""XTB6xx — SIMD intrinsics confinement (the kernel-dispatch seam).

The native kernels are vectorized under a bitwise determinism contract
(docs/native_threading.md): every intrinsic body has a scalar twin with
identical per-element semantics, runtime CPU dispatch picks between them,
and the lane-width fuzz tests pin scalar == vector.  That contract is only
auditable while ALL raw intrinsics live in the one designated seam header,
``native/xtb_simd.h`` — an ``_mm256_*`` call sprinkled into a kernel body
bypasses the scalar fallback, the runtime dispatch, *and* the fuzz axis.

- **XTB601** — a raw SIMD intrinsic, vector type, or intrinsics header
  include appears in a native C++ file other than ``xtb_simd.h``.

The scan is textual (the C++ sources have no AST here): intrinsic name
patterns (``_mm*_``/``__m128/256/512``/NEON ``v*q_*`` load-store-arith
families) and the intrinsics headers (``immintrin.h``, ``arm_neon.h``,
...).  Calls *into* the seam (``xtb_simd_*``, ``xtb_hist_sweep_avx2``)
are the sanctioned surface and do not match.
"""
from __future__ import annotations

import os
import re
from typing import Iterable, List

from .core import Finding, Project, Rule

# the one file allowed to contain intrinsics
ALLOWED_BASENAME = "xtb_simd.h"

_PATTERNS = (
    # x86: _mm_*, _mm256_*, _mm512_* intrinsic calls and vector types
    re.compile(r"\b_mm\d*_\w+\s*\("),
    re.compile(r"\b__m(?:128|256|512)[id]?\b"),
    # NEON: vector types and the common intrinsic families
    re.compile(r"\b(?:float|int|uint)(?:8|16|32|64)x\d+(?:x\d+)?_t\b"),
    re.compile(r"\bv(?:ld|st)\d\w*_\w+\s*\("),
    re.compile(r"\bv(?:add|sub|mul|div|max|min|abs|bsl|and|orr|mvn|cge|cgt|"
               r"dup|reinterpret)q?\w*_\w+\s*\("),
    # the headers themselves
    re.compile(r"#\s*include\s*[<\"](?:immintrin|x86intrin|emmintrin|"
               r"smmintrin|tmmintrin|avxintrin|avx2intrin|arm_neon|arm_sve)"
               r"\.h[>\"]"),
)

_NATIVE_EXTS = (".cc", ".cpp", ".cxx", ".h", ".hpp", ".c")


class SimdSeamRule(Rule):
    """XTB601 — raw intrinsics outside native/xtb_simd.h."""

    name = "simd-seam"
    codes = {
        "XTB601": "raw SIMD intrinsics outside the dispatch seam "
                  "(native/xtb_simd.h)",
    }

    def native_dir(self, project: Project) -> str:
        if not project.docs_root:
            return ""
        return os.path.join(os.path.dirname(project.docs_root), "native")

    def finalize(self, project: Project) -> Iterable[Finding]:
        nd = self.native_dir(project)
        if not nd or not os.path.isdir(nd):
            return ()  # subtree lint / snippet mode: nothing to check
        findings: List[Finding] = []
        for name in sorted(os.listdir(nd)):
            if not name.endswith(_NATIVE_EXTS) or name == ALLOWED_BASENAME:
                continue
            path = os.path.join(nd, name)
            try:
                with open(path, encoding="utf-8", errors="replace") as fh:
                    text = fh.read()
            except OSError:
                continue
            findings.extend(self.check_text(text, path))
        return findings

    def check_text(self, text: str, path: str) -> List[Finding]:
        findings: List[Finding] = []
        for i, line in enumerate(text.splitlines(), start=1):
            for pat in _PATTERNS:
                m = pat.search(line)
                if m:
                    findings.append(Finding(
                        path, i, m.start(), "XTB601",
                        f"raw SIMD token {m.group(0).strip()!r} outside "
                        f"native/{ALLOWED_BASENAME}; vector bodies belong "
                        f"in the dispatch seam with a scalar twin "
                        f"(docs/native_threading.md)"))
                    break  # one finding per line is enough
        return findings
