"""Collective communication API (reference: python-package/xgboost/collective.py,
src/collective/ — the rabit-descended flat API).

On TPU the mesh IS the communicator: jax.distributed supplies rendezvous
(replacing the RabitTracker socket bootstrap, tracker.h:141) and XLA
collectives carry the data.  The flat functions below dispatch through a thin
swappable **backend trait** — the role of the reference's ``Coll`` interface +
``CommGroup`` backend select (src/collective/coll.h:23, comm_group.cc:99) — so
single-process, multi-process (jax.distributed), and the in-process test fake
(src/collective/in_memory_communicator.h:18) stay interchangeable without the
callers (growers, sketch merge, metrics) knowing which one is live.
"""
from __future__ import annotations

import contextlib
import threading
import time
from enum import IntEnum
from typing import Any, Dict, List, Optional

import numpy as np

from .elastic import RegroupRequired
from .reliability import watchdog as _watchdog
from .reliability.faults import maybe_inject as _maybe_inject

__all__ = [
    "init", "finalize", "get_rank", "get_world_size", "is_distributed",
    "communicator_print", "get_processor_name", "broadcast", "allreduce",
    "allgather", "allgather_ragged", "signal_error", "Op",
    "global_sum", "global_max", "global_ratio",
    "regroup", "regroup_pending", "RegroupRequired",
    "CommunicatorContext", "CollBackend",
]


class Op(IntEnum):
    """Reduce ops (reference: Op enum, src/collective/comm.h:186)."""

    MAX = 0
    MIN = 1
    SUM = 2
    BITWISE_AND = 3
    BITWISE_OR = 4
    BITWISE_XOR = 5


_REDUCERS = {
    Op.SUM: np.sum, Op.MAX: np.max, Op.MIN: np.min,
    Op.BITWISE_AND: np.bitwise_and.reduce,
    Op.BITWISE_OR: np.bitwise_or.reduce,
    Op.BITWISE_XOR: np.bitwise_xor.reduce,
}


def _reduce_stacked(gathered: np.ndarray, op: Op, dtype) -> np.ndarray:
    red = _REDUCERS.get(op)
    if red is None:
        raise NotImplementedError(f"allreduce op {op!r} not supported")
    return red(gathered, axis=0).astype(dtype)


def _platform_hint() -> str:
    """The REQUESTED jax platform ("cpu", "tpu", ... or "" when unset),
    from jax.config / JAX_PLATFORMS — without initializing any backend
    (jax.default_backend() would, and jax.distributed.initialize must run
    first on accelerator clusters)."""
    import os

    import jax

    hint = ""
    try:
        hint = jax.config.jax_platforms or ""
    except AttributeError:
        pass
    hint = hint or os.environ.get("JAX_PLATFORMS", "")
    return hint.split(",")[0].strip().lower()


_TRANSIENT_RENDEZVOUS = ("deadline", "unavailable", "connection", "refused",
                         "timed out", "timeout", "reset")


def _init_jax_distributed(*, coordinator_address, num_processes,
                          process_id) -> None:
    """jax.distributed rendezvous with elastic retry/backoff: a coordinator
    that is still binding its port (worker raced the launcher) or briefly
    unreachable (restart) is retried with jittered exponential backoff
    instead of failing the whole job on the first refused connection.
    ``XGBOOST_TPU_RENDEZVOUS_RETRIES`` (default 3) bounds the re-attempts;
    retries count into ``xtb_retries_total{op="jax.rendezvous"}``."""
    import os

    import jax

    from .reliability.retry import retry_call

    def _initialize():
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )

    retry_call(
        _initialize, op="jax.rendezvous",
        retries=int(os.environ.get("XGBOOST_TPU_RENDEZVOUS_RETRIES", "3")),
        base=0.5, max_delay=15.0,
        seed=int(process_id) if process_id is not None else 0,
        # jax surfaces rendezvous failures as RuntimeError, but so are
        # permanent conditions ("already initialized", misconfiguration) —
        # only grpc-transient-looking messages are worth re-attempting,
        # the rest must fail immediately with the real error
        retry_on=(RuntimeError, OSError),
        retry_if=lambda e: (isinstance(e, OSError)
                            or any(s in str(e).lower()
                                   for s in _TRANSIENT_RENDEZVOUS)))


# ---------------------------------------------------------------------------
# Backend trait (Coll, coll.h:23)
# ---------------------------------------------------------------------------


class CollBackend:
    """Abstract collective backend: rank/world + allgather is the complete
    primitive set — allreduce and broadcast are derived (an ordered host
    reduction over the gathered stack is what makes multi-worker training
    bitwise deterministic, the property the reference engineers with
    quantised integer allreduce, quantiser.cuh:52)."""

    def rank(self) -> int:
        raise NotImplementedError

    def world_size(self) -> int:
        raise NotImplementedError

    def allgather(self, data: np.ndarray) -> np.ndarray:
        """(world, *data.shape) — every worker's identically-shaped array."""
        raise NotImplementedError

    def allreduce(self, data: np.ndarray, op: Op) -> np.ndarray:
        return _reduce_stacked(self.allgather(data), op, data.dtype)

    def broadcast_bytes(self, payload: Optional[bytes], root: int) -> bytes:
        """Default: length-prefixed gather-based broadcast."""
        me = self.rank()
        n = np.asarray([len(payload) if me == root else 0], np.int64)
        size = int(self.allgather(n)[root, 0])
        buf = np.zeros(size, np.uint8)
        if me == root:
            buf[:] = np.frombuffer(payload, np.uint8)
        return bytes(self.allgather(buf)[root])

    def regroup_pending(self) -> bool:
        """True when elastic group membership changed and this worker has
        not yet regrouped (checked by ``train()`` at round boundaries)."""
        return False

    def regroup(self, completed_round: int):
        """Join the elastic regroup barrier; returns the new
        ``(rank, world)``.  Only elastic-capable backends implement it."""
        raise RuntimeError(
            f"{type(self).__name__} is not elastic: regroup is only "
            "supported on tracker-relay and in-memory backends")

    def shutdown(self) -> None:
        pass


class SingleProcessBackend(CollBackend):
    """world_size == 1 identity (the reference degrades the same way)."""

    def rank(self) -> int:
        return 0

    def world_size(self) -> int:
        return 1

    def allgather(self, data: np.ndarray) -> np.ndarray:
        return np.asarray(data)[None]

    def allreduce(self, data: np.ndarray, op: Op) -> np.ndarray:
        return np.asarray(data).copy()

    def broadcast_bytes(self, payload, root):
        return payload


class JaxDistributedBackend(CollBackend):
    """Multi-process backend over jax.distributed + host allgather
    (the RabitComm/NCCLComm role; rendezvous = jax coordinator service)."""

    def __init__(self, **args: Any) -> None:
        self._tracker = None
        self._relay_mode = False
        if args.get("dmlc_tracker_uri") and args.get("dmlc_tracker_port"):
            # tracker mode (reference flow): dmlc_* args address a
            # RabitTracker rendezvous server, which assigns the rank,
            # relays rank 0's jax.distributed coordinator address, and
            # stays connected as the error channel (TrackerClient watcher).
            # dmlc_task_id is a sort hint (sortby="task"), not a rank.
            from .tracker import TrackerClient

            self._tracker = TrackerClient(
                str(args["dmlc_tracker_uri"]),
                int(args["dmlc_tracker_port"]),
                task_id=str(args.get("dmlc_task_id", "")))
            import os

            # XLA's CPU backend cannot execute multiprocess collectives
            # (jaxlib raises INVALID_ARGUMENT at the first gather), so on
            # CPU the tracker's socket relay carries them instead and
            # jax.distributed is skipped entirely; accelerator backends
            # keep the native path.  XGBOOST_TPU_COLL=relay|jax overrides.
            # The platform is read from config/env, NOT jax.default_backend():
            # probing the backend would initialize XLA, and
            # jax.distributed.initialize must run before any computation —
            # the probe would break the accelerator path it selects.
            mode = os.environ.get("XGBOOST_TPU_COLL", "auto")
            self._relay_mode = (
                self._tracker.coll_port is not None
                and self._tracker.world > 1
                and (mode == "relay"
                     or (mode == "auto" and _platform_hint() == "cpu")))
            if self._relay_mode:
                return
            _init_jax_distributed(
                coordinator_address=self._tracker.coordinator,
                num_processes=self._tracker.world,
                process_id=self._tracker.rank,
            )
            return
        if args.get("dmlc_tracker_uri") or args.get("dmlc_tracker_port"):
            # partially-specified rendezvous must fail loudly, not silently
            # run single-process (a worker that meant to join a job and
            # didn't would train on its shard alone and produce a wrong model)
            raise ValueError(
                "tracker rendezvous needs BOTH dmlc_tracker_uri and "
                f"dmlc_tracker_port; got uri={args.get('dmlc_tracker_uri')!r} "
                f"port={args.get('dmlc_tracker_port')!r}")
        # direct mode: the caller runs its own rendezvous and passes the
        # jax coordinator address + pre-assigned rank (launcher.py flow)
        coordinator = args.get("coordinator_address")
        n_proc = args.get("num_processes")
        rank = args.get("process_id")
        if coordinator is not None:
            _init_jax_distributed(
                coordinator_address=str(coordinator),
                num_processes=int(n_proc) if n_proc is not None else None,
                process_id=int(rank) if rank is not None else None,
            )

    def rank(self) -> int:
        if self._relay_mode:
            return self._tracker.rank
        import jax

        return jax.process_index()

    def world_size(self) -> int:
        if self._relay_mode:
            return self._tracker.world
        import jax

        return jax.process_count()

    def allgather(self, data: np.ndarray) -> np.ndarray:
        if self._relay_mode:
            return self._tracker.coll_allgather(np.asarray(data))
        if self.world_size() == 1:
            return np.asarray(data)[None]
        from jax.experimental import multihost_utils

        # gather every process's contribution (host-local arrays are NOT
        # globally addressable, so a psum over a replicated operand would be
        # wrong), then reduce on host — exact for every Op incl. bitwise
        return np.asarray(multihost_utils.process_allgather(data))

    def broadcast_bytes(self, payload: Optional[bytes], root: int) -> bytes:
        if self._relay_mode:
            # derived gather-based broadcast over the relay (CollBackend)
            return super().broadcast_bytes(payload, root)
        if self.world_size() == 1:
            return payload
        from jax.experimental import multihost_utils

        is_root = self.rank() == root
        arr = (np.frombuffer(payload, np.uint8) if is_root else None)
        n = multihost_utils.broadcast_one_to_all(
            np.asarray([len(arr) if is_root else 0], np.int64),
            is_source=is_root)
        buf = np.zeros(int(n[0]), np.uint8)
        if is_root:
            buf[:] = arr
        out = multihost_utils.broadcast_one_to_all(buf, is_source=is_root)
        return bytes(np.asarray(out))

    def regroup_pending(self) -> bool:
        t = self._tracker
        return bool(self._relay_mode and t is not None
                    and t.regroup_pending)

    def regroup(self, completed_round: int):
        if self._tracker is None or not self._relay_mode:
            raise RuntimeError(
                "elastic regroup requires tracker rendezvous with relay "
                "collectives (CPU tracker mode): a jax.distributed world "
                "is fixed at initialize() and cannot rescale")
        self._tracker.regroup(int(completed_round))
        return self._tracker.rank, self._tracker.world

    def shutdown(self) -> None:
        relay = self._relay_mode
        self._relay_mode = False
        if self._tracker is not None:
            self._tracker.shutdown()
            self._tracker = None
        if relay:
            return  # jax.distributed was never initialized
        try:
            import jax

            jax.distributed.shutdown()
        except Exception:
            pass


class _InMemoryJoiner:
    """A thread waiting to be absorbed by the group's next regroup."""

    def __init__(self) -> None:
        self.event = threading.Event()
        self.rank: Optional[int] = None
        self.epoch: Optional[int] = None


class _InMemoryGroup:
    """Shared rendezvous state for thread workers in one process.

    Elastic state mirrors the tracker protocol in miniature so the
    regroup logic is exercisable in-process (tier-1, no subprocess
    spawn): ``departed`` ranks leave via :meth:`InMemoryBackend.leave`
    (aborting the barrier so blocked peers surface
    :class:`RegroupRequired`), joiners park on the group, and the last
    live member to call ``regroup`` forms the next epoch — compacted
    ranks, fresh barrier, joiners appended."""

    def __init__(self, world: int) -> None:
        self.world = world
        self.barrier = threading.Barrier(world)
        self.slots: List[Optional[np.ndarray]] = [None] * world
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.epoch = 0
        self.regroup_pending = False
        self.departed: set = set()
        self.joiners: List[_InMemoryJoiner] = []
        self.waiters: Dict[int, int] = {}  # rank -> completed round
        self.assignment: Optional[tuple] = None  # (epoch, {old: new})


_INMEM_GROUPS: Dict[str, _InMemoryGroup] = {}
_INMEM_LOCK = threading.Lock()


class InMemoryBackend(CollBackend):
    """In-process multi-worker fake: N threads, shared-memory exchange
    (reference: src/collective/in_memory_communicator.h:18 +
    in_memory_handler.h:68 — used by the thread-worker test harness,
    tests/cpp/collective/test_worker.h:155).  Select with
    ``dmlc_communicator='in-memory'`` plus world size/rank/group args.

    Elastic shrink/absorb works here too (``leave()`` /
    ``join=True``), giving the regroup state machine quick-tier
    coverage with no subprocess spawn (tests/test_elastic.py)."""

    def __init__(self, world: Optional[int] = None,
                 rank: Optional[int] = None, group: str = "default",
                 join: bool = False, join_timeout: float = 600.0) -> None:
        self._group_name = group
        self._epoch = 0
        if join:
            # replacement worker: park on the existing group; the next
            # regroup assigns our rank (absorption at a round boundary)
            with _INMEM_LOCK:
                g = _INMEM_GROUPS.get(group)
            if g is None:
                raise RuntimeError(
                    f"in-memory group {group!r} does not exist; a joiner "
                    "needs a live cohort to be absorbed into")
            tok = _InMemoryJoiner()
            with g.cond:
                g.joiners.append(tok)
                g.regroup_pending = True
                # snapshot BEFORE formation: _try_form_epoch may install
                # the next epoch's fresh barrier, which must not be the
                # one we abort (Barrier.abort() is permanent)
                stale_barrier = g.barrier
                # members may ALL be parked in regroup() already
                self._try_form_epoch(g)
                g.cond.notify_all()
            # wake members blocked mid-gather: they re-enter via regroup
            stale_barrier.abort()
            if not tok.event.wait(timeout=join_timeout):
                raise RuntimeError("in-memory join timed out (no regroup)")
            with g.cond:
                self._group = g
                self._rank = int(tok.rank)
                self._world = g.world
                self._epoch = int(tok.epoch)
            return
        if world is None or rank is None:
            raise TypeError("InMemoryBackend needs world and rank "
                            "(or join=True)")
        self._world = world
        self._rank = rank
        with _INMEM_LOCK:
            g = _INMEM_GROUPS.get(group)
            # a failed cohort leaves its barrier aborted; a fresh cohort
            # must not inherit the poisoned group
            if g is None or g.world != world or g.barrier.broken:
                g = _INMEM_GROUPS[group] = _InMemoryGroup(world)
        self._group = g

    def rank(self) -> int:
        return self._rank

    def world_size(self) -> int:
        return self._world

    def allgather(self, data: np.ndarray) -> np.ndarray:
        g = self._group
        with g.cond:
            if g.regroup_pending:
                raise RegroupRequired(
                    "in-memory group membership changed")
        g.slots[self._rank] = np.asarray(data)
        try:
            # bounded (XTB701): a thread worker wedged forever breaks the
            # barrier for everyone, surfacing an error instead of a hang
            g.barrier.wait(timeout=600.0)  # all slots filled
            out = np.stack([np.asarray(s) for s in g.slots])
            g.barrier.wait(timeout=600.0)  # everyone copied before reuse
        except threading.BrokenBarrierError:
            with g.cond:
                if g.regroup_pending:
                    raise RegroupRequired(
                        "in-memory group membership changed") from None
            raise
        return out

    # ------------------------------------------------------------ elastic
    @staticmethod
    def _try_form_epoch(g: _InMemoryGroup) -> None:
        """Form the next epoch once every LIVE member is parked in
        regroup() (``g.cond`` must be held).  Called from regroup() on
        each arrival AND from leave()/join registration — a departure or
        joiner while the others are already parked must re-evaluate
        formation, or the parked survivors would wait out the timeout."""
        live = [r for r in range(g.world) if r not in g.departed]
        if not g.regroup_pending or not (set(g.waiters) >= set(live)):
            return
        joiners = list(g.joiners)
        new_world = len(live) + len(joiners)
        if new_world == 0:
            return  # nobody left to form an epoch for
        g.joiners = []
        mapping = {old: new for new, old in enumerate(sorted(live))}
        g.world = new_world
        g.barrier = threading.Barrier(new_world)
        g.slots = [None] * new_world
        g.departed = set()
        g.waiters = {}
        g.regroup_pending = False
        g.epoch += 1
        g.assignment = (g.epoch, mapping)
        for k, tok in enumerate(joiners):
            tok.rank = len(live) + k
            tok.epoch = g.epoch
        g.cond.notify_all()
        for tok in joiners:
            tok.event.set()

    def leave(self) -> None:
        """Deterministic preemption: depart the group (the in-memory
        equivalent of a worker process dying).  Peers blocked in a gather
        get :class:`RegroupRequired` through the aborted barrier; peers
        already parked in regroup() are re-checked for epoch formation."""
        g = self._group
        with g.cond:
            g.departed.add(self._rank)
            g.regroup_pending = True
            # snapshot first: _try_form_epoch may have just installed the
            # new epoch's barrier, and aborting THAT would poison the
            # epoch the survivors are about to train on
            stale_barrier = g.barrier
            self._try_form_epoch(g)
            g.cond.notify_all()
        stale_barrier.abort()

    def regroup_pending(self) -> bool:
        g = self._group
        with g.cond:
            return g.regroup_pending

    def regroup(self, completed_round: int):
        g = self._group
        with g.cond:
            g.waiters[self._rank] = int(completed_round)
            target = self._epoch + 1
            self._try_form_epoch(g)
            while not (g.assignment is not None
                       and g.assignment[0] >= target):
                if not g.cond.wait(timeout=600.0):
                    raise RuntimeError("in-memory regroup timed out")
            epoch, mapping = g.assignment
            if self._rank not in mapping:
                raise RuntimeError(
                    f"departed rank {self._rank} cannot regroup")
            self._rank = mapping[self._rank]
            self._world = g.world
            self._epoch = epoch
        return self._rank, self._world


# ---------------------------------------------------------------------------
# Flat API (communicator-inl.h role) over the selected backend
# ---------------------------------------------------------------------------

# thread-local so in-memory thread workers each see their own rank; falls
# back to the process-wide backend for ordinary (one worker per process) use
_TLS = threading.local()
_PROCESS_BACKEND: Optional[CollBackend] = None
# argless construction skips jax.distributed.initialize: used only to QUERY
# rank/world when someone else (a launcher) already initialized jax
_DEFAULT = JaxDistributedBackend()


def _backend() -> CollBackend:
    b = getattr(_TLS, "backend", None)
    if b is not None:
        return b
    if _PROCESS_BACKEND is not None:
        return _PROCESS_BACKEND
    # not init()-ed: report jax.distributed state if someone else set it up
    return _DEFAULT


_coll_seq = 0  # liveness counter: collectives completed in this process


def _coll_stall(op) -> None:
    """Collective-wait watchdog stall stage: sever the relay socket so
    the blocked thread surfaces ``RegroupRequired`` and drains into the
    elastic regroup — a wedged collective becomes a membership change,
    not a hang.  A no-op on backends without an interruptible relay
    (jax.distributed owns its own liveness there)."""
    t = getattr(_backend(), "_tracker", None)
    if t is not None and hasattr(t, "interrupt_collective"):
        t.interrupt_collective()


def _coll_progress() -> None:
    """Advance the liveness marker the tracker's stall monitor compares
    between telemetry ships: a worker completing collectives is alive
    however slow its rounds look."""
    global _coll_seq
    _coll_seq += 1
    _watchdog.progress("collective", seq=_coll_seq)


_coll_hist = None  # xtb_coll_wait_seconds family (lazy; import stays cheap)
_slow_coll = None  # xtb_net_slow_coll_total (lazy, same pattern)
_LINK_BUDGET: Any = "unset"  # lazily resolved XGBOOST_TPU_LINK_TIMEOUT_S


def _link_budget_s() -> Optional[float]:
    """The per-link collective deadline (``XGBOOST_TPU_LINK_TIMEOUT_S``),
    read once: the same budget the tracker relay uses to declare a
    never-contributing rank lost, applied here as the worker-local
    slow-link attribution threshold."""
    global _LINK_BUDGET
    if _LINK_BUDGET == "unset":
        from .tracker import _link_timeout_s

        _LINK_BUDGET = _link_timeout_s()
    return _LINK_BUDGET


def _observe_wait(op: str, t0: float) -> None:
    """Record one collective's blocked wall into
    ``xtb_coll_wait_seconds{op,rank}`` — the per-rank straggler signal: a
    FAST rank spends its round waiting in collectives for the slow one,
    so the rank with the largest wait is pointing at the straggler, per
    op.  Shipped snapshots merge these driver-side, where the per-rank
    labels make cross-rank comparison one scrape
    (docs/observability.md § Distributed observability).

    A wait past the per-link deadline additionally counts into
    ``xtb_net_slow_coll_total{op,rank}`` with a flight fault: this rank
    finished its own work and then waited on a slow or partitioned peer
    longer than the deadline the relay holds links to — the worker-local
    side of slow-peer attribution (docs/reliability.md "Degraded
    networks")."""
    global _coll_hist, _slow_coll
    if _coll_hist is None:
        from .telemetry.registry import get_registry

        _coll_hist = get_registry().histogram(
            "xtb_coll_wait_seconds",
            "seconds blocked in collective operations, by op and rank",
            ("op", "rank"))
    try:
        rank = get_rank()
    except Exception:  # pragma: no cover - backend mid-teardown
        rank = -1
    wait = time.perf_counter() - t0
    _coll_hist.labels(op, str(rank)).observe(wait)
    budget = _link_budget_s()
    if budget is not None and wait > budget:
        if _slow_coll is None:
            from .telemetry.registry import get_registry

            _slow_coll = get_registry().counter(
                "xtb_net_slow_coll_total",
                "collectives whose blocked wall exceeded the per-link "
                "deadline (this rank waited on a slow or partitioned "
                "peer)", ("op", "rank"))
        _slow_coll.labels(op, str(rank)).inc()
        from .telemetry import flight as _flight

        _flight.record("fault", "collective.slow_link", op=op,
                       rank=rank, wait_s=round(wait, 3),
                       budget_s=budget)


def _reconcile_native_kernels() -> None:
    """All ranks must run the SAME split/hist implementation: the native FFI
    scan differs from the XLA formulation in the last f32 ulp, and every
    process redundantly evaluates splits on the allreduced histogram — a
    rank missing the kernels (failed build, no toolchain) picking the XLA
    path while its peers take the native one could choose a different
    near-tie split and silently diverge the trees.  Allreduce-MIN the local
    availability; if anyone lacks it, everyone vetoes (utils/native.py)."""
    import jax

    from .utils import native

    if jax.default_backend() != "cpu" or get_world_size() <= 1:
        return
    ok = np.asarray([1 if native.load_ffi() else 0], np.int32)
    unanimous = int(allreduce(ok, Op.MIN)[0])
    if not unanimous and not native.FFI_DISTRIBUTED_VETO:
        native.FFI_DISTRIBUTED_VETO = True
        # programs traced before the veto have the native custom calls baked
        # in; drop them so every post-init trace takes the XLA path
        jax.clear_caches()


def _reconcile_with_regroup() -> None:
    """Init-time kernel reconcile that survives a membership change: the
    epoch-0 first collective can race a peer death or a tracker failover
    (re-adoption sets the regroup flag before anything trains), and
    nothing above ``init()`` catches ``RegroupRequired`` — so join the
    regroup here and retry; the new epoch replays the reconcile as its
    first collective anyway."""
    while True:
        try:
            _reconcile_native_kernels()
            return
        except RegroupRequired:
            _backend().regroup(0)


def init(**args: Any) -> None:
    """Initialize the collective (reference: collective.py:94 init).

    Backend select (comm_group.cc:99): ``dmlc_communicator`` /
    ``xgboost_communicator`` = 'in-memory' picks the in-process fake
    (args: in_memory_world_size / in_memory_rank / in_memory_group);
    anything else maps the reference's rabit args onto jax.distributed.
    """
    global _PROCESS_BACKEND
    kind = (args.get("dmlc_communicator")
            or args.get("xgboost_communicator") or "").replace("_", "-")
    if kind == "in-memory":
        group = str(args.get("in_memory_group", "default"))
        if args.get("in_memory_join"):
            # elastic replacement: absorbed by the group's next regroup
            _TLS.backend = InMemoryBackend(
                group=group, join=True,
                join_timeout=float(args.get("in_memory_join_timeout",
                                            600.0)))
            _reconcile_native_kernels()
            return
        world = int(args.get("in_memory_world_size", 1))
        rank = int(args.get("in_memory_rank", 0))
        _TLS.backend = InMemoryBackend(world, rank, group)
        _reconcile_native_kernels()
        return
    if kind == "federated":
        from .federated import FederatedBackend

        # reference parameter names: plugin/federated/federated_comm.cc
        _TLS.backend = FederatedBackend(
            str(args["federated_server_address"]),
            int(args["federated_world_size"]),
            int(args["federated_rank"]),
            server_cert_path=str(args.get("federated_server_cert_path", "")),
            client_key_path=str(args.get("federated_client_key_path", "")),
            client_cert_path=str(args.get("federated_client_cert_path", "")))
        _reconcile_native_kernels()
        return
    _PROCESS_BACKEND = JaxDistributedBackend(**args)
    _reconcile_with_regroup()


def finalize() -> None:
    global _PROCESS_BACKEND
    # final telemetry ship BEFORE the channel closes: the driver-side
    # merged registry keeps this worker's last numbers after the process
    # is gone (best-effort; no tracker backend = no-op)
    try:
        from .telemetry import distributed as _distributed

        _distributed.ship_to_tracker(force=True)
    except Exception:  # pragma: no cover - observability must not fail exit
        pass
    b = getattr(_TLS, "backend", None)
    if b is not None:
        b.shutdown()
        _TLS.backend = None
        return
    if _PROCESS_BACKEND is not None:
        _PROCESS_BACKEND.shutdown()
        _PROCESS_BACKEND = None


def get_rank() -> int:
    return _backend().rank()


def get_world_size() -> int:
    return _backend().world_size()


def is_distributed() -> bool:
    return get_world_size() > 1


def get_processor_name() -> str:
    import socket

    return socket.gethostname()


def communicator_print(msg: str) -> None:
    print(f"[{get_rank()}] {msg}", flush=True)


def allreduce(data: np.ndarray, op: Op = Op.SUM) -> np.ndarray:
    """Allreduce across workers (reference: collective.py allreduce) —
    exact and identically ordered on every worker."""
    # seam: delay (slow peer), exception (failed exchange -> caller's
    # signal_error path), kill (worker death mid-collective); no-op
    # without an installed plan (one global read)
    _maybe_inject("collective.allreduce", rank=get_rank)
    t0 = time.perf_counter()
    with _watchdog.guard("collective.wait", op="allreduce",
                         on_stall=_coll_stall):
        out = _backend().allreduce(np.asarray(data), op)
    _coll_progress()
    _observe_wait("allreduce", t0)
    return out


def allgather(data: np.ndarray) -> np.ndarray:
    """Gather each worker's (identically-shaped) array: (world, *shape).

    The building block of the distributed quantile-sketch merge
    (reference: src/common/quantile.cc:397 AllreduceV of summaries)."""
    _maybe_inject("collective.allgather", rank=get_rank)
    t0 = time.perf_counter()
    with _watchdog.guard("collective.wait", op="allgather",
                         on_stall=_coll_stall):
        out = _backend().allgather(np.asarray(data))
    _coll_progress()
    _observe_wait("allgather", t0)
    return out


def allgather_ragged(data: np.ndarray) -> np.ndarray:
    """Concatenate 1-D/2-D row-arrays of differing per-worker lengths
    (pad-to-max allgather, then trim)."""
    data = np.asarray(data)
    if not is_distributed():
        return data
    sizes = allgather(np.asarray([data.shape[0]], np.int64))[:, 0]
    width = int(sizes.max())
    pad = np.zeros((width,) + data.shape[1:], data.dtype)
    pad[: data.shape[0]] = data
    stacked = allgather(pad)  # (world, width, ...)
    return np.concatenate([stacked[k, : sizes[k]] for k in range(len(sizes))])


def global_sum(values: np.ndarray) -> np.ndarray:
    """Allreduce-SUM sugar (reference: src/collective/aggregator.h:33
    GlobalSum)."""
    return allreduce(np.asarray(values), Op.SUM)


def global_max(value) -> np.ndarray:
    """Allreduce-MAX sugar (aggregator.h:23 GlobalMax)."""
    return allreduce(np.asarray(value), Op.MAX)


def global_ratio(dividend: float, divisor: float) -> float:
    """sum(dividend) / sum(divisor) across workers; NaN when the global
    divisor is <= 0 (aggregator.h:52 GlobalRatio — the merge shape every
    distributed metric uses)."""
    out = allreduce(np.asarray([dividend, divisor], np.float64), Op.SUM)
    return float(out[0] / out[1]) if out[1] > 0 else float("nan")


def regroup_pending() -> bool:
    """True when elastic group membership changed (a worker died or a
    replacement is waiting) and this worker has not yet regrouped.
    ``train(..., elastic=...)`` polls this at every round boundary."""
    return _backend().regroup_pending()


def regroup(completed_round: int = 0):
    """Join the elastic regroup barrier and adopt the next epoch's
    ``(rank, world)`` — returned as a tuple.  Blocks until every live
    member has reached its round boundary (dead members are detected and
    excluded by the tracker); parked replacement workers are absorbed
    into the new epoch.  Raises on non-elastic backends.

    The caller (``train()``) is responsible for reloading model state
    from the last checkpoint and rebuilding its data shard from the
    rebalanced :class:`~xgboost_tpu.elastic.ShardMap` afterwards —
    docs/reliability.md § Elastic training."""
    # seam: delay (slow member holding up the barrier), exception
    # (regroup machinery fault -> job failure path), kill (death during
    # the regroup itself — the tracker completes with the remainder)
    _maybe_inject("collective.regroup", rank=get_rank)
    t0 = time.perf_counter()
    out = _backend().regroup(int(completed_round))
    _observe_wait("regroup", t0)
    # re-run the kernel reconcile as the new epoch's FIRST collective: an
    # absorbed replacement runs it during init(), so survivors must replay
    # it too or the epoch's relay seq numbering diverges between them —
    # and a joiner lacking the native kernels must still veto everyone
    _reconcile_native_kernels()
    return out


def broadcast(data: Any, root: int) -> Any:
    """Broadcast a python object from root (reference: collective.py broadcast)."""
    if not is_distributed():
        return data
    import pickle

    b = _backend()
    payload = pickle.dumps(data) if b.rank() == root else None
    return pickle.loads(b.broadcast_bytes(payload, root))


def signal_error(msg: str = "") -> None:
    """Fail-fast error signal (reference: collective.py:319 signal_error —
    the tracker broadcasts the failure and every worker exits).

    MUST NOT synchronize: get_rank() would trigger jax backend init, which
    under jax.distributed runs a cross-process topology barrier — blocking
    forever when a peer is already wedged, i.e. exactly when this function
    is called.  The reference keeps a dedicated error socket for the same
    reason (comm.cc:503 SignalError writes the tracker port directly)."""
    import sys

    b = _backend()
    tracker = getattr(b, "_tracker", None)
    rank = getattr(tracker, "rank", "?")
    print(f"[{rank}] collective error: {msg}", flush=True)
    if tracker is not None:
        tracker.signal_error(msg or "signal_error")
    sys.exit(1)


class CommunicatorContext:
    """with-block wrapper (reference: collective.py:358)."""

    def __init__(self, **args: Any) -> None:
        self.args = args

    def __enter__(self) -> Dict[str, Any]:
        init(**self.args)
        return self.args

    def __exit__(self, *exc: Any) -> None:
        finalize()
