"""Collective communication API (reference: python-package/xgboost/collective.py,
src/collective/ — the rabit-descended flat API).

On TPU the mesh IS the communicator: jax.distributed supplies rendezvous
(replacing the RabitTracker socket bootstrap, tracker.h:141) and XLA
collectives carry the data, so ``init``/``CommunicatorContext`` configure
jax.distributed while ``allreduce``/``broadcast`` run tiny jitted psum/select
programs over the live devices.  Single-process (no distributed init) is the
identity backend — mirroring how the reference degrades to world_size == 1.
"""
from __future__ import annotations

import contextlib
from enum import IntEnum
from typing import Any, Dict, Optional

import numpy as np

__all__ = [
    "init", "finalize", "get_rank", "get_world_size", "is_distributed",
    "communicator_print", "get_processor_name", "broadcast", "allreduce",
    "allgather", "signal_error", "Op", "CommunicatorContext",
]

_INITIALIZED = False


class Op(IntEnum):
    """Reduce ops (reference: Op enum, src/collective/comm.h:186)."""

    MAX = 0
    MIN = 1
    SUM = 2
    BITWISE_AND = 3
    BITWISE_OR = 4
    BITWISE_XOR = 5


def init(**args: Any) -> None:
    """Initialize the collective (reference: collective.py:94 init).

    Accepts the reference's args and maps the distributed ones onto
    jax.distributed.initialize; a no-op when single-process.
    """
    global _INITIALIZED
    coordinator = args.get("dmlc_tracker_uri") or args.get("coordinator_address")
    n_proc = args.get("dmlc_nworker")
    if n_proc is None:
        n_proc = args.get("num_processes")
    rank = args.get("dmlc_task_id")  # 0 is a valid rank: no `or` chains
    if rank is None:
        rank = args.get("process_id")
    if coordinator is not None:
        import jax

        port = args.get("dmlc_tracker_port")
        addr = f"{coordinator}:{port}" if port else str(coordinator)
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=int(n_proc) if n_proc is not None else None,
            process_id=int(rank) if rank is not None else None,
        )
    _INITIALIZED = True


def finalize() -> None:
    global _INITIALIZED
    if _INITIALIZED:
        try:
            import jax

            jax.distributed.shutdown()
        except Exception:
            pass
        _INITIALIZED = False


def get_rank() -> int:
    import jax

    return jax.process_index()


def get_world_size() -> int:
    import jax

    return jax.process_count()


def is_distributed() -> bool:
    return get_world_size() > 1


def get_processor_name() -> str:
    import socket

    return socket.gethostname()


def communicator_print(msg: str) -> None:
    print(f"[{get_rank()}] {msg}", flush=True)


def allreduce(data: np.ndarray, op: Op = Op.SUM) -> np.ndarray:
    """Allreduce across processes (reference: collective.py allreduce).

    Gathers each process's contribution (multihost process_allgather) and
    reduces on host — exact for sum/min/max and the bitwise ops; the
    single-process case is an identity copy.
    """
    data = np.asarray(data)
    if not is_distributed():
        return data.copy()
    from jax.experimental import multihost_utils

    # gather every process's contribution (host-local arrays are NOT globally
    # addressable, so a psum over a replicated operand would be wrong), then
    # reduce on host — exact for every Op incl. the bitwise ones
    gathered = np.asarray(multihost_utils.process_allgather(data))
    red = {Op.SUM: np.sum, Op.MAX: np.max, Op.MIN: np.min,
           Op.BITWISE_AND: np.bitwise_and.reduce,
           Op.BITWISE_OR: np.bitwise_or.reduce,
           Op.BITWISE_XOR: np.bitwise_xor.reduce}.get(op)
    if red is None:
        raise NotImplementedError(f"allreduce op {op!r} not supported")
    return red(gathered, axis=0).astype(data.dtype)


def allgather(data: np.ndarray) -> np.ndarray:
    """Gather each process's (identically-shaped) array: (world, *shape).

    The building block of the distributed quantile-sketch merge
    (reference: src/common/quantile.cc:397 AllreduceV of summaries)."""
    data = np.asarray(data)
    if not is_distributed():
        return data[None]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(data))


def allgather_ragged(data: np.ndarray) -> np.ndarray:
    """Concatenate 1-D/2-D row-arrays of differing per-process lengths
    (pad-to-max allgather, then trim)."""
    data = np.asarray(data)
    if not is_distributed():
        return data
    sizes = allgather(np.asarray([data.shape[0]], np.int64))[:, 0]
    width = int(sizes.max())
    pad = np.zeros((width,) + data.shape[1:], data.dtype)
    pad[: data.shape[0]] = data
    stacked = allgather(pad)  # (world, width, ...)
    return np.concatenate([stacked[k, : sizes[k]] for k in range(len(sizes))])


def broadcast(data: Any, root: int) -> Any:
    """Broadcast python object from root (reference: collective.py broadcast)."""
    if not is_distributed():
        return data
    import pickle

    from jax.experimental import multihost_utils

    is_root = get_rank() == root
    payload = np.frombuffer(pickle.dumps(data), dtype=np.uint8) if is_root else None
    # two-step: fixed-shape length broadcast, then the padded payload
    n = multihost_utils.broadcast_one_to_all(
        np.asarray([len(payload) if is_root else 0], np.int64), is_source=is_root
    )
    size = int(n[0])
    buf = np.zeros(size, np.uint8)
    if is_root:
        buf[:] = payload
    out = multihost_utils.broadcast_one_to_all(buf, is_source=is_root)
    return pickle.loads(bytes(np.asarray(out)))


def signal_error(msg: str = "") -> None:
    """Fail-fast error signal (reference: collective.py:319 signal_error —
    the tracker broadcasts the failure and every worker exits)."""
    import sys

    communicator_print(f"collective error: {msg}")
    sys.exit(1)


class CommunicatorContext:
    """with-block wrapper (reference: collective.py:358)."""

    def __init__(self, **args: Any) -> None:
        self.args = args

    def __enter__(self) -> Dict[str, Any]:
        init(**self.args)
        return self.args

    def __exit__(self, *exc: Any) -> None:
        finalize()
