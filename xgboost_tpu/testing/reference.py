"""Pure-numpy reference implementation of hist tree growing.

The correctness oracle for the device kernels (the role xgboost-CPU plays in
the reference's GPU↔CPU parity tests, tests/python-gpu/test_gpu_updaters.py).
Implements the same semantics as ops/histogram.py + ops/split.py +
tree/grow.py with plain loops: any divergence is a bug in one of them.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


def threshold_l1(g, alpha):
    return np.sign(g) * np.maximum(np.abs(g) - alpha, 0.0)


def calc_weight(G, H, lam, alpha, max_delta_step):
    if H <= 0:
        return 0.0
    w = -threshold_l1(G, alpha) / (H + lam)
    if max_delta_step > 0:
        w = float(np.clip(w, -max_delta_step, max_delta_step))
    return float(w)


def calc_gain(G, H, lam, alpha, max_delta_step):
    if H <= 0:
        return 0.0
    if max_delta_step == 0.0:
        return float(threshold_l1(G, alpha) ** 2 / (H + lam))
    w = calc_weight(G, H, lam, alpha, max_delta_step)
    return float(-(2.0 * threshold_l1(G, alpha) * w + (H + lam) * w * w))


def build_hist_np(bins, gpair, rows, n_bin):
    """(F, B, 2) histogram over the given row subset; sentinel bins ignored."""
    F = bins.shape[1]
    hist = np.zeros((F, n_bin, 2), np.float64)
    for r in rows:
        for f in range(F):
            b = int(bins[r, f])
            if b < n_bin:
                hist[f, b, 0] += gpair[r, 0]
                hist[f, b, 1] += gpair[r, 1]
    return hist


def best_split_np(hist, total, n_bins_arr, lam, alpha, mds, min_child_weight, eps=1e-6):
    """Mirror of ops/split.py evaluate_splits for one node. Returns dict or None."""
    F, B, _ = hist.shape
    parent_gain = calc_gain(total[0], total[1], lam, alpha, mds)
    best = None
    for f in range(F):
        nb = int(n_bins_arr[f])
        feat_sum = hist[f, :, :].sum(axis=0)
        miss = total - feat_sum
        for dleft in (True, False):
            GL = HL = 0.0
            if dleft:
                GL, HL = miss[0], miss[1]
            for b in range(nb):
                GL += hist[f, b, 0]
                HL += hist[f, b, 1]
                if b == nb - 1:
                    # top bin only valid when missing mass goes right
                    if dleft or abs(miss[1]) <= eps:
                        continue
                GR, HR = total[0] - GL, total[1] - HL
                if HL < min_child_weight or HR < min_child_weight or HL <= 0 or HR <= 0:
                    continue
                gain = (
                    calc_gain(GL, HL, lam, alpha, mds)
                    + calc_gain(GR, HR, lam, alpha, mds)
                    - parent_gain
                )
                # tie-break identical to device: flat argmax over (f, b) with
                # default-left preferred on exact ties
                key = (gain, -(f * B + b), dleft)
                if best is None or key > (best["gain"], -(best["f"] * B + best["b"]), best["dleft"]):
                    best = dict(gain=gain, f=f, b=b, dleft=dleft,
                                left=(GL, HL), right=(GR, HR))
    return best


def grow_tree_np(bins, gpair, n_bin, n_bins_arr, max_depth, lam=1.0, alpha=0.0,
                 mds=0.0, min_child_weight=1.0, gamma=0.0, eta=0.3):
    """Depthwise growth over heap node ids; returns dict heap arrays like
    tree/grow.py TreeState (host mirror)."""
    R = bins.shape[0]
    max_nodes = (1 << (max_depth + 1)) - 1
    feat = np.full(max_nodes, -1, np.int32)
    sbin = np.zeros(max_nodes, np.int32)
    dleft = np.ones(max_nodes, bool)
    leaf_val = np.zeros(max_nodes, np.float64)
    is_leaf = np.zeros(max_nodes, bool)
    totals = np.zeros((max_nodes, 2), np.float64)
    rows_of = {0: np.arange(R)}
    totals[0] = gpair.sum(axis=0)
    gamma_eps = max(gamma, 1e-6)

    for d in range(max_depth + 1):
        for node in range((1 << d) - 1, (1 << (d + 1)) - 1):
            rows = rows_of.get(node)
            if rows is None:
                continue
            total = totals[node]
            if d == max_depth:
                is_leaf[node] = True
                leaf_val[node] = eta * calc_weight(total[0], total[1], lam, alpha, mds)
                continue
            hist = build_hist_np(bins, gpair, rows, n_bin)
            best = best_split_np(hist, total, n_bins_arr, lam, alpha, mds, min_child_weight)
            if best is None or best["gain"] <= gamma_eps:
                is_leaf[node] = True
                leaf_val[node] = eta * calc_weight(total[0], total[1], lam, alpha, mds)
                continue
            feat[node] = best["f"]
            sbin[node] = best["b"]
            dleft[node] = best["dleft"]
            f, b = best["f"], best["b"]
            bv = bins[rows, f]
            go_left = np.where(bv >= n_bin, best["dleft"], bv <= b)
            rows_of[2 * node + 1] = rows[go_left]
            rows_of[2 * node + 2] = rows[~go_left]
            totals[2 * node + 1] = best["left"]
            totals[2 * node + 2] = best["right"]
    return dict(feat=feat, sbin=sbin, dleft=dleft, leaf_val=leaf_val,
                is_leaf=is_leaf, totals=totals, rows_of=rows_of)
