"""Test data generators (reference: python-package/xgboost/testing/data.py —
make_sparse_regression:933, make_categorical:1034, make_ltr:813; C++
RandomDataGenerator tests/cpp/helpers.h:224)."""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def make_regression(n: int = 1000, f: int = 10, *, sparsity: float = 0.0,
                    seed: int = 0, noise: float = 0.1) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    w = rng.normal(size=f)
    y = (X @ w + noise * rng.normal(size=n)).astype(np.float32)
    if sparsity > 0:
        mask = rng.random((n, f)) < sparsity
        X[mask] = np.nan
    return X, y


def make_binary(n: int = 1000, f: int = 10, *, sparsity: float = 0.0,
                seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    X, y = make_regression(n, f, sparsity=sparsity, seed=seed, noise=0.5)
    return X, (y > np.median(y)).astype(np.float32)


def make_multiclass(n: int = 1000, f: int = 10, k: int = 4, *, seed: int = 0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=2.0, size=(k, f))
    y = rng.integers(0, k, size=n)
    X = (centers[y] + rng.normal(size=(n, f))).astype(np.float32)
    return X, y.astype(np.float32)


def make_ltr(n_query: int = 30, max_docs: int = 40, f: int = 8, *, seed: int = 0):
    """Learning-to-rank data: (X, relevance, qid) with graded labels 0-4."""
    rng = np.random.default_rng(seed)
    Xs, ys, qids = [], [], []
    for q in range(n_query):
        nd = int(rng.integers(2, max_docs))
        Xq = rng.normal(size=(nd, f)).astype(np.float32)
        score = Xq[:, 0] + 0.5 * Xq[:, 1] + 0.3 * rng.normal(size=nd)
        rel = np.clip(np.digitize(score, [-1.0, -0.3, 0.3, 1.0]), 0, 4)
        Xs.append(Xq)
        ys.append(rel.astype(np.float32))
        qids.append(np.full(nd, q, np.int64))
    return np.concatenate(Xs), np.concatenate(ys), np.concatenate(qids)


def make_sparse_csr(n: int = 500, f: int = 20, density: float = 0.2, seed: int = 0):
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    M = sp.random(n, f, density=density, random_state=np.random.RandomState(seed),
                  format="csr", dtype=np.float32)
    y = np.asarray(M.sum(axis=1)).ravel() + 0.1 * rng.normal(size=n)
    return M, y.astype(np.float32)


def make_categorical(n: int = 500, num_f: int = 4, cat_f: int = 3, n_cats: int = 6,
                     seed: int = 0, as_pandas: bool = True):
    rng = np.random.default_rng(seed)
    import pandas as pd

    cols = {}
    y = np.zeros(n)
    for i in range(num_f):
        v = rng.normal(size=n)
        cols[f"num{i}"] = v.astype(np.float32)
        y += v * rng.normal()
    for i in range(cat_f):
        codes = rng.integers(0, n_cats, size=n)
        effect = rng.normal(size=n_cats)
        y += effect[codes]
        cols[f"cat{i}"] = pd.Categorical.from_codes(codes, categories=[f"c{j}" for j in range(n_cats)])
    df = pd.DataFrame(cols)
    return df, (y + 0.1 * rng.normal(size=n)).astype(np.float32)
