
"""Shared test-support surface (reference: python-package/xgboost/testing)."""
import os

# The reference-xgboost oracle the parity suites train against (built by
# oracle/build_oracle.sh; durable under /root so /tmp wipes can't silently
# disable parity checking).  Single source of truth for every consumer:
# tests/test_oracle_parity.py, tests/test_exact.py, tests/conftest.py.
ORACLE_PKG = "/root/oracle_build/pkg"
HAVE_ORACLE = os.path.exists(os.path.join(ORACLE_PKG, "xgboost", "lib",
                                          "libxgboost.so"))
