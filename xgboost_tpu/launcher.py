"""Multi-process training launcher — the role of the reference's dask/spark
launchers (python-package/xgboost/dask/__init__.py:722 _train_async: one
worker per data shard, rabit rendezvous, identical models out).

There is no dask in the TPU stack: jax.distributed is the rendezvous and the
collective, so the launcher's job reduces to spawning one process per worker
with the coordinator address wired through ``collective.init``.  Each worker
runs ``fn(rank, world_size)``; inside, build a DMatrix on the worker's shard
and call ``xgboost_tpu.train`` — cuts merge through the distributed sketch
and histograms allreduce per level, so every worker returns the same model
(tested in tests/test_multiprocess.py).

Example worker::

    def worker(rank, world):
        import xgboost_tpu as xtb
        X, y = load_shard(rank, world)
        bst = xtb.train(params, xtb.DMatrix(X, label=y), 100)
        if rank == 0:
            bst.save_model("model.ubj")

    from xgboost_tpu.launcher import run_distributed
    run_distributed(worker, num_workers=4)
"""
from __future__ import annotations

import functools
import os
import pickle
import socket
import subprocess
import sys
import tempfile
from typing import Callable, Optional


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class WorkerFailedError(RuntimeError):
    """One or more spawned workers exited non-zero.

    ``failures`` holds ``(label, returncode, stderr_tail)`` per failed
    worker — ``label`` is the spawn index (the tracker may have assigned a
    different collective rank; the worker's own stderr says which), and
    ``stderr_tail`` is the captured tail of that process's stderr, so the
    first-failure cause survives instead of every peer's death reading as
    a generic rendezvous hang."""

    def __init__(self, message: str, failures) -> None:
        super().__init__(message)
        self.failures = list(failures)


def stderr_tail(path: str, limit: int = 4000) -> str:
    """Last ``limit`` bytes of a spawned worker's captured stderr file
    (what :class:`WorkerFailedError.failures` carries per corpse)."""
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(size - limit, 0))
            return fh.read().decode("utf-8", "replace").strip()
    except OSError:
        return "<stderr unavailable>"


def spawn_worker(argv, label, err_files: dict, *, env=None):
    """Spawn one worker subprocess with per-process stderr capture.

    The launcher's stderr-to-file discipline as a reusable primitive (the
    serving fleet spawns replicas through it): stderr goes to a temp file
    recorded in ``err_files[label]`` — not a pipe, since nobody drains
    pipes while workers run and the tail must survive the process — so a
    death surfaces its actual cause via :func:`stderr_tail`, not a bare
    exit code.  The child also inherits a flight-recorder identity
    (``XGBOOST_TPU_FLIGHT_DIR``/``_LABEL``), so its crash/spill dump
    lands at :func:`flight_dump_path` for this label.  Returns the
    ``subprocess.Popen``; the caller owns reaping and unlinking
    ``err_files`` values."""
    from .telemetry import flight

    fd, err_path = tempfile.mkstemp(prefix=f"xtb_worker_{label}_",
                                    suffix=".stderr")
    err_files[label] = err_path
    env = dict(env if env is not None else os.environ)
    env.setdefault(flight.ENV_DIR, flight.dump_dir())
    env[flight.ENV_LABEL] = str(label)
    with os.fdopen(fd, "wb") as ef:
        return subprocess.Popen(argv, env=env, stderr=ef)


def flight_dump_path(label) -> Optional[str]:
    """The flight-recorder dump a worker spawned with ``label`` would
    have left (crash dump, periodic spill, or atexit) — None when the
    process never wrote one (e.g. SIGKILL before the first spill)."""
    from .telemetry import flight

    path = flight.default_path(str(label))
    return path if os.path.exists(path) else None


def stack_dump_path(label) -> Optional[str]:
    """The all-thread ``faulthandler`` dump a worker spawned with
    ``label`` would have left (crash path, injected kill, watchdog dump
    stage) — None when none was written."""
    from .telemetry import flight

    path = flight.stacks_path(str(label))
    return path if os.path.exists(path) else None


def _postmortem_tail(label, tail: str) -> str:
    """Append the flight-recorder and stack-dump pointers a corpse left
    to its stderr tail (what WorkerFailedError.failures carries)."""
    fp = flight_dump_path(label)
    if fp:
        tail += f"\n[flight recorder: {fp}]"
    sp = stack_dump_path(label)
    if sp:
        tail += f"\n[stack dump: {sp}]"
    return tail


_TRACKER_CHILD = r"""
import sys

host, port, world = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
elastic, journal = sys.argv[4] == "1", sys.argv[5]
if sys.argv[6]:
    sys.path.insert(0, sys.argv[6])  # the xgboost_tpu package root

from xgboost_tpu.telemetry import flight, profiler
from xgboost_tpu.tracker import RabitTracker

flight.install()  # label "tracker"/"tracker_r<N>" from the launcher env
profiler.maybe_start("tracker")  # relay loops join the merged flame view
tr = RabitTracker(n_workers=world, host_ip=host, port=port,
                  elastic=elastic, journal=journal)
tr.start()
try:
    # block until the job finishes; the LAUNCHER owns the overall
    # deadline and kills this process when the run is over or failed
    tr.wait_for(timeout=0)
except RuntimeError:
    # the job failed — the abort already fanned out to every worker.
    # Exit 1 tells the launcher "job error", distinct from a crash
    # (any other status), which is what triggers a respawn.
    sys.exit(1)
finally:
    tr.free()
"""


def _tracker_connectable(port: int, deadline_s: float = 30.0) -> bool:
    """Poll until the tracker child accepts connections (its import +
    bind window).  The probe connection EOFs without a handshake, which
    the tracker's accept loops already treat as a stray scan."""
    import time

    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        try:
            socket.create_connection(("127.0.0.1", int(port)),
                                     timeout=1.0).close()
            return True
        except OSError:
            time.sleep(0.1)
    return False


_CHILD = r"""
import pickle, sys
import jax

platform = sys.argv[4]
if platform:
    jax.config.update("jax_platforms", platform)
if sys.argv[6]:
    sys.path.insert(0, sys.argv[6])  # make fn's defining module importable
from xgboost_tpu import collective
from xgboost_tpu.telemetry import flight, profiler, trace

flight.install()  # ring spill + crash dump under the launcher's label env
profiler.maybe_start()  # default-on sampler; label set by training.train

rank = sys.argv[1]  # spawn label; an int only in direct mode ("respawn<N>"
                    # labels exist in elastic tracker mode)
world = int(sys.argv[2])
port = sys.argv[3]
if sys.argv[7] == "tracker":
    # tracker rendezvous: rank assigned by the tracker, persistent abort
    # channel, socket-relay collectives on CPU backends (tracker.CollRelay)
    collective.init(dmlc_tracker_uri="127.0.0.1", dmlc_tracker_port=port,
                    dmlc_nworker=world)
    rank = collective.get_rank()
    # elastic replacements join at the CURRENT world size, not the
    # originally requested one
    world = collective.get_world_size()
else:
    rank = int(rank)
    collective.init(coordinator_address=f"127.0.0.1:{port}",
                    num_processes=world, process_id=rank)
if trace.active():
    trace.set_process_name(f"rank{rank}")
with open(sys.argv[5], "rb") as fh:
    fn = pickle.load(fh)
try:
    fn(rank, world)
except BaseException as e:
    # postmortem without tracing: the ring of recent spans/events/faults
    # survives as a dump the launcher attaches to WorkerFailedError —
    # plus an all-thread faulthandler dump (what were the OTHER threads
    # doing: prefetch pools, relay watchers, telemetry shippers)
    flight.record("fault", "worker.crash", error=repr(e))
    flight.dump_stacks()
    flight.dump()
    raise
finally:
    collective.finalize()
"""


def run_distributed(fn: Callable[[int, int], None], num_workers: int,
                    *, coordinator_port: Optional[int] = None,
                    platform: Optional[str] = None,
                    timeout: float = 3600.0,
                    fault_plan: Optional[str] = None,
                    rendezvous: str = "auto",
                    elastic: bool = False,
                    max_respawns: int = 0,
                    tracker_failover: bool = False,
                    max_tracker_respawns: int = 3) -> dict:
    """Spawn ``num_workers`` processes, each running ``fn(rank, world)``
    under an initialized collective.  ``fn`` must be picklable (a module-
    level function).  ``platform`` overrides jax_platforms in the workers
    (e.g. "cpu" for tests; the sitecustomize freeze means the env var alone
    is not enough).  Raises on the first failing worker.

    ``fault_plan``: inline JSON or a file path, exported to the workers as
    ``XGBOOST_TPU_FAULT_PLAN`` (reliability/faults.py) — the hook the
    fault-injection tests and the nightly kill/resume smoke use.

    ``rendezvous``: "direct" (jax.distributed coordinator, pre-assigned
    ranks) or "tracker" (a RabitTracker assigns ranks, keeps the abort
    fan-out channel, and supplies socket-relay collectives on CPU backends
    — required for CPU multi-process training, docs/reliability.md).
    "auto" picks "tracker" for CPU workers (XLA:CPU cannot run
    multiprocess collectives, and the abort fan-out is strictly more
    robust locally) and "direct" for accelerator platforms.

    ``elastic``: the tracker runs in elastic mode — a worker dying no
    longer fails the job; the survivors regroup at world N-1 and keep
    training (workers must pass ``train(..., elastic=...)`` for the data
    re-sharding side).  Requires tracker rendezvous.  ``max_respawns``
    bounds how many replacement workers the launcher spawns after deaths;
    each connects to the tracker and is absorbed at the next round
    boundary.  Exit code 255 (tracker abort fan-out: an explicitly
    signalled error) still fails the job even in elastic mode.

    ``tracker_failover``: the tracker runs as a SUPERVISED SUBPROCESS
    journaling its replayable state (roster, epoch, per-rank resume
    rounds — reliability/journal.py); a crashed/SIGKILL'd tracker is
    respawned (up to ``max_tracker_respawns`` times) and recovers from
    the journal, the surviving workers re-adopt with backoff, and the
    run continues through an elastic regroup at the same world size —
    bitwise-identical model bytes under deterministic config (the
    coordinator stops being a single point of failure;
    docs/reliability.md "Coordinator failover & watchdog").  Requires
    ``elastic=True``.  A respawned tracker starts with a CLEAN fault-plan
    environment, so a plan that killed the first tracker cannot re-kill
    every successor.  Note the merged-telemetry ingest then happens in
    the tracker subprocess, not this driver.

    Failures raise :class:`WorkerFailedError` carrying each failed
    worker's spawn index, exit code, and captured stderr tail.  Returns a
    stats dict: tolerated worker deaths, worker respawns, tracker
    respawns, and each tracker-respawn pause wall (death detection to
    the respawned tracker accepting again) in seconds."""
    tracker = None
    tracker_proc = None
    journal_dir = None
    # opt-in driver-side scrape endpoint (XGBOOST_TPU_METRICS_PORT): the
    # tracker ingests worker snapshot ships into the merged registry, and
    # /metrics serves per-rank plus merged series while the job runs
    from .telemetry.distributed import start_metrics_server

    start_metrics_server()
    if rendezvous == "auto":
        rendezvous = "tracker" if (platform or "") == "cpu" else "direct"
    if elastic and rendezvous != "tracker":
        raise ValueError("elastic mode requires rendezvous='tracker' "
                         "(relay collectives re-form at regroup; a "
                         "jax.distributed world cannot rescale)")
    if tracker_failover and (rendezvous != "tracker" or not elastic):
        raise ValueError("tracker_failover requires rendezvous='tracker' "
                         "AND elastic=True: a re-adopted cohort recovers "
                         "through the elastic regroup + checkpoint path")
    if rendezvous == "tracker" and not tracker_failover:
        from .tracker import RabitTracker

        tracker = RabitTracker(n_workers=num_workers, host_ip="127.0.0.1",
                               elastic=elastic)
        tracker.start()
        port = tracker.port
    elif rendezvous == "tracker":
        port = _free_port()  # the tracker child binds it (and rebinds it
        #                      on every respawn — workers only know this
        #                      address)
        journal_dir = tempfile.mkdtemp(prefix="xtb_tracker_journal_")
    elif rendezvous == "direct":
        port = coordinator_port or _free_port()
    else:
        raise ValueError(f"unknown rendezvous {rendezvous!r}")
    with tempfile.NamedTemporaryFile(suffix=".pkl", delete=False) as fh:
        pickle.dump(fn, fh)
        fn_path = fh.name
    target = fn
    while isinstance(target, functools.partial):
        target = target.func  # resolve the real function's home module
    mod = sys.modules.get(getattr(target, "__module__", ""), None)
    mod_dir = (os.path.dirname(os.path.abspath(mod.__file__))
               if mod is not None and getattr(mod, "__file__", None) else "")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    if fault_plan is not None:
        env["XGBOOST_TPU_FAULT_PLAN"] = fault_plan
    import time

    err_files = {}

    def _spawn(label):
        return spawn_worker(
            [sys.executable, "-c", _CHILD, str(label),
             str(num_workers), str(port), platform or "", fn_path,
             mod_dir, rendezvous],
            label, err_files, env=env)

    tracker_respawns = 0
    tracker_pauses = []  # seconds, death detection -> accepting again

    def _spawn_tracker(label):
        t_env = dict(env)
        if tracker_respawns:
            # a respawned coordinator must start with a clean plan: the
            # per-process seam counters restart at 0, so the spec that
            # killed the first tracker would re-fire in every successor
            t_env.pop("XGBOOST_TPU_FAULT_PLAN", None)
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        argv = [sys.executable, "-c", _TRACKER_CHILD, "127.0.0.1",
                str(port), str(num_workers), "1" if elastic else "0",
                os.path.join(journal_dir, "tracker.xtbjrnl"), pkg_root]
        return spawn_worker(argv, label, err_files, env=t_env)

    if tracker_failover:
        tracker_proc = _spawn_tracker("tracker")
        if not _tracker_connectable(port):
            tracker_proc.kill()
            raise WorkerFailedError(
                "tracker subprocess never became connectable; stderr "
                "tail:\n" + stderr_tail(err_files["tracker"]),
                [("tracker", tracker_proc.poll(),
                  stderr_tail(err_files["tracker"]))])

    pending = {rank: _spawn(rank) for rank in range(num_workers)}
    respawned = 0
    succeeded = 0
    tolerated = []  # (label, rc) deaths survived in elastic mode
    try:
        deadline = time.monotonic() + timeout
        failures = []  # (label, rc, stderr_tail)
        while pending:
            if tracker_proc is not None:
                rc_t = tracker_proc.poll()
                if rc_t is not None:
                    if rc_t == 1:
                        # the tracker declared the JOB failed (it already
                        # fanned the abort out): stop supervising; the
                        # workers' 255 exits carry the failure below
                        tracker_proc = None
                    elif rc_t == 0:
                        # clean completion: the workers are finishing too
                        tracker_proc = None
                    elif tracker_respawns >= max_tracker_respawns:
                        for p in pending.values():
                            p.kill()
                        raise WorkerFailedError(
                            f"tracker crashed (exit {rc_t}) with the "
                            f"respawn budget ({max_tracker_respawns}) "
                            "spent", [("tracker", rc_t,
                                       stderr_tail(err_files.get(
                                           f"tracker_r{tracker_respawns}"
                                           if tracker_respawns
                                           else "tracker", "")))])
                    else:
                        # coordinator crash (SIGKILL, injected kill, bug):
                        # respawn it against the journal — the workers
                        # are re-adopting with backoff meanwhile, and the
                        # pause ends when the new tracker accepts
                        t0 = time.monotonic()
                        tracker_respawns += 1
                        print(f"[launcher] tracker exited {rc_t}; "
                              f"respawning against the journal "
                              f"({tracker_respawns}/{max_tracker_respawns})",
                              flush=True)
                        tracker_proc = _spawn_tracker(
                            f"tracker_r{tracker_respawns}")
                        if not _tracker_connectable(port):
                            for p in pending.values():
                                p.kill()
                            raise WorkerFailedError(
                                "respawned tracker never became "
                                "connectable",
                                [("tracker", rc_t, stderr_tail(
                                    err_files[
                                        f"tracker_r{tracker_respawns}"]))])
                        tracker_pauses.append(time.monotonic() - t0)
            for label, p in list(pending.items()):
                rc = p.poll()
                if rc is None:
                    continue
                del pending[label]
                if rc == 0:
                    succeeded += 1
                    continue
                tail = stderr_tail(err_files[label])
                # a death after peers already finished is still a
                # survivable death (a watchdog-declared stall wakes and
                # dies LAST, after the survivors completed the run) —
                # only "nobody succeeded and nobody is left" is fatal
                survivors_exist = succeeded > 0
                # a death during the initial rendezvous cannot be
                # regrouped (the tracker is still collecting the cohort);
                # tolerating it would leave the survivors blocked in
                # their handshakes until the full job timeout.  With a
                # subprocess tracker the journal's existence IS the
                # rendezvous-complete signal: its first record is the
                # initial roster.
                regroupable = (
                    (tracker is not None and tracker.rendezvous_complete)
                    or (journal_dir is not None and os.path.exists(
                        os.path.join(journal_dir, "tracker.xtbjrnl"))))
                if (elastic and rc != 255 and regroupable
                        and (pending or survivors_exist)):
                    # a death the survivors absorb (rc 255 means the
                    # tracker itself declared the job failed)
                    tolerated.append((label, rc))
                    print(f"[launcher] elastic: worker {label} exited "
                          f"{rc}; {len(pending)} continuing"
                          + (f"\n--- worker {label} stderr tail ---\n{tail}"
                             if tail else ""), flush=True)
                    if respawned < max_respawns:
                        respawned += 1
                        new_label = f"respawn{respawned}"
                        pending[new_label] = _spawn(new_label)
                    continue
                failures.append((label, rc, tail))
            if failures:
                # fail fast: peers would otherwise block in rendezvous or a
                # collective forever, waiting for the dead worker
                for p in pending.values():
                    p.kill()
                # attach each corpse's flight-recorder dump (crash dump or
                # last periodic spill) and its all-thread faulthandler
                # stack dump — the pair that makes the postmortem possible
                # without tracing or a debugger
                failures = [(r, rc, _postmortem_tail(r, tail))
                            for r, rc, tail in failures]
                labels = [f[0] for f in failures]
                detail = ", ".join(
                    f"rank {r}: " + ("aborted by tracker fan-out"
                                     if rc == 255 else f"exit {rc}")
                    for r, rc, _t in failures)
                msg = (f"worker(s) {labels} exited non-zero ({detail}); "
                       f"remaining workers killed")
                for r, _rc, tail in failures:
                    if tail:
                        msg += (f"\n--- worker {r} stderr tail ---\n{tail}")
                raise WorkerFailedError(msg, failures)
            if pending and time.monotonic() > deadline:
                for p in pending.values():
                    p.kill()
                raise TimeoutError(
                    f"worker(s) {sorted(pending, key=str)} still running "
                    f"after {timeout}s; killed")
            if pending:
                time.sleep(0.2)
    finally:
        if tracker is not None:
            tracker.free()
        if tracker_proc is not None:
            tracker_proc.kill()
        if journal_dir is not None:
            import shutil

            shutil.rmtree(journal_dir, ignore_errors=True)
        try:
            os.unlink(fn_path)
        except OSError:
            pass
        for path in err_files.values():
            try:
                os.unlink(path)
            except OSError:
                pass
    return {"tolerated": list(tolerated), "respawned": respawned,
            "succeeded": succeeded, "tracker_respawns": tracker_respawns,
            "tracker_pauses_s": tracker_pauses}
