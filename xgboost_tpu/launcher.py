"""Multi-process training launcher — the role of the reference's dask/spark
launchers (python-package/xgboost/dask/__init__.py:722 _train_async: one
worker per data shard, rabit rendezvous, identical models out).

There is no dask in the TPU stack: jax.distributed is the rendezvous and the
collective, so the launcher's job reduces to spawning one process per worker
with the coordinator address wired through ``collective.init``.  Each worker
runs ``fn(rank, world_size)``; inside, build a DMatrix on the worker's shard
and call ``xgboost_tpu.train`` — cuts merge through the distributed sketch
and histograms allreduce per level, so every worker returns the same model
(tested in tests/test_multiprocess.py).

Example worker::

    def worker(rank, world):
        import xgboost_tpu as xtb
        X, y = load_shard(rank, world)
        bst = xtb.train(params, xtb.DMatrix(X, label=y), 100)
        if rank == 0:
            bst.save_model("model.ubj")

    from xgboost_tpu.launcher import run_distributed
    run_distributed(worker, num_workers=4)
"""
from __future__ import annotations

import functools
import os
import pickle
import socket
import subprocess
import sys
import tempfile
from typing import Callable, Optional


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_CHILD = r"""
import pickle, sys
import jax

platform = sys.argv[4]
if platform:
    jax.config.update("jax_platforms", platform)
if sys.argv[6]:
    sys.path.insert(0, sys.argv[6])  # make fn's defining module importable
from xgboost_tpu import collective

rank = int(sys.argv[1])
world = int(sys.argv[2])
port = sys.argv[3]
if sys.argv[7] == "tracker":
    # tracker rendezvous: rank assigned by the tracker, persistent abort
    # channel, socket-relay collectives on CPU backends (tracker.CollRelay)
    collective.init(dmlc_tracker_uri="127.0.0.1", dmlc_tracker_port=port,
                    dmlc_nworker=world)
    rank = collective.get_rank()
else:
    collective.init(coordinator_address=f"127.0.0.1:{port}",
                    num_processes=world, process_id=rank)
with open(sys.argv[5], "rb") as fh:
    fn = pickle.load(fh)
try:
    fn(rank, world)
finally:
    collective.finalize()
"""


def run_distributed(fn: Callable[[int, int], None], num_workers: int,
                    *, coordinator_port: Optional[int] = None,
                    platform: Optional[str] = None,
                    timeout: float = 3600.0,
                    fault_plan: Optional[str] = None,
                    rendezvous: str = "auto") -> None:
    """Spawn ``num_workers`` processes, each running ``fn(rank, world)``
    under an initialized collective.  ``fn`` must be picklable (a module-
    level function).  ``platform`` overrides jax_platforms in the workers
    (e.g. "cpu" for tests; the sitecustomize freeze means the env var alone
    is not enough).  Raises on the first failing worker.

    ``fault_plan``: inline JSON or a file path, exported to the workers as
    ``XGBOOST_TPU_FAULT_PLAN`` (reliability/faults.py) — the hook the
    fault-injection tests and the nightly kill/resume smoke use.

    ``rendezvous``: "direct" (jax.distributed coordinator, pre-assigned
    ranks) or "tracker" (a RabitTracker assigns ranks, keeps the abort
    fan-out channel, and supplies socket-relay collectives on CPU backends
    — required for CPU multi-process training, docs/reliability.md).
    "auto" picks "tracker" for CPU workers (XLA:CPU cannot run
    multiprocess collectives, and the abort fan-out is strictly more
    robust locally) and "direct" for accelerator platforms."""
    tracker = None
    if rendezvous == "auto":
        rendezvous = "tracker" if (platform or "") == "cpu" else "direct"
    if rendezvous == "tracker":
        from .tracker import RabitTracker

        tracker = RabitTracker(n_workers=num_workers, host_ip="127.0.0.1")
        tracker.start()
        port = tracker.port
    elif rendezvous == "direct":
        port = coordinator_port or _free_port()
    else:
        raise ValueError(f"unknown rendezvous {rendezvous!r}")
    with tempfile.NamedTemporaryFile(suffix=".pkl", delete=False) as fh:
        pickle.dump(fn, fh)
        fn_path = fh.name
    target = fn
    while isinstance(target, functools.partial):
        target = target.func  # resolve the real function's home module
    mod = sys.modules.get(getattr(target, "__module__", ""), None)
    mod_dir = (os.path.dirname(os.path.abspath(mod.__file__))
               if mod is not None and getattr(mod, "__file__", None) else "")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    if fault_plan is not None:
        env["XGBOOST_TPU_FAULT_PLAN"] = fault_plan
    import time

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(rank), str(num_workers),
             str(port), platform or "", fn_path, mod_dir, rendezvous],
            env=env)
        for rank in range(num_workers)
    ]
    try:
        deadline = time.monotonic() + timeout
        errs = []
        rcs = {}
        pending = dict(enumerate(procs))
        while pending:
            for rank, p in list(pending.items()):
                rc = p.poll()
                if rc is None:
                    continue
                del pending[rank]
                if rc != 0:
                    errs.append(rank)
                    rcs[rank] = rc
            if errs:
                # fail fast: peers would otherwise block in rendezvous or a
                # collective forever, waiting for the dead worker
                for p in pending.values():
                    p.kill()
                detail = ", ".join(
                    f"rank {r}: " + ("aborted by tracker fan-out"
                                     if rcs[r] == 255 else f"exit {rcs[r]}")
                    for r in errs)
                raise RuntimeError(f"worker(s) {errs} exited non-zero "
                                   f"({detail}); remaining workers killed")
            if pending and time.monotonic() > deadline:
                for p in pending.values():
                    p.kill()
                raise TimeoutError(
                    f"worker(s) {sorted(pending)} still running after "
                    f"{timeout}s; killed")
            if pending:
                time.sleep(0.2)
    finally:
        if tracker is not None:
            tracker.free()
        try:
            os.unlink(fn_path)
        except OSError:
            pass
