"""Multi-process training launcher — the role of the reference's dask/spark
launchers (python-package/xgboost/dask/__init__.py:722 _train_async: one
worker per data shard, rabit rendezvous, identical models out).

There is no dask in the TPU stack: jax.distributed is the rendezvous and the
collective, so the launcher's job reduces to spawning one process per worker
with the coordinator address wired through ``collective.init``.  Each worker
runs ``fn(rank, world_size)``; inside, build a DMatrix on the worker's shard
and call ``xgboost_tpu.train`` — cuts merge through the distributed sketch
and histograms allreduce per level, so every worker returns the same model
(tested in tests/test_multiprocess.py).

Example worker::

    def worker(rank, world):
        import xgboost_tpu as xtb
        X, y = load_shard(rank, world)
        bst = xtb.train(params, xtb.DMatrix(X, label=y), 100)
        if rank == 0:
            bst.save_model("model.ubj")

    from xgboost_tpu.launcher import run_distributed
    run_distributed(worker, num_workers=4)
"""
from __future__ import annotations

import os
import pickle
import socket
import subprocess
import sys
import tempfile
from typing import Callable, Optional


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_CHILD = r"""
import pickle, sys
import jax

platform = sys.argv[4]
if platform:
    jax.config.update("jax_platforms", platform)
if sys.argv[6]:
    sys.path.insert(0, sys.argv[6])  # make fn's defining module importable
from xgboost_tpu import collective

rank = int(sys.argv[1])
world = int(sys.argv[2])
port = sys.argv[3]
collective.init(coordinator_address=f"127.0.0.1:{port}",
                num_processes=world, process_id=rank)
with open(sys.argv[5], "rb") as fh:
    fn = pickle.load(fh)
try:
    fn(rank, world)
finally:
    collective.finalize()
"""


def run_distributed(fn: Callable[[int, int], None], num_workers: int,
                    *, coordinator_port: Optional[int] = None,
                    platform: Optional[str] = None,
                    timeout: float = 3600.0) -> None:
    """Spawn ``num_workers`` processes, each running ``fn(rank, world)``
    under an initialized collective.  ``fn`` must be picklable (a module-
    level function).  ``platform`` overrides jax_platforms in the workers
    (e.g. "cpu" for tests; the sitecustomize freeze means the env var alone
    is not enough).  Raises on the first failing worker."""
    port = coordinator_port or _free_port()
    with tempfile.NamedTemporaryFile(suffix=".pkl", delete=False) as fh:
        pickle.dump(fn, fh)
        fn_path = fh.name
    mod = sys.modules.get(getattr(fn, "__module__", ""), None)
    mod_dir = (os.path.dirname(os.path.abspath(mod.__file__))
               if mod is not None and getattr(mod, "__file__", None) else "")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    import time

    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(rank), str(num_workers),
             str(port), platform or "", fn_path, mod_dir],
            env=env)
        for rank in range(num_workers)
    ]
    try:
        deadline = time.monotonic() + timeout
        errs = []
        pending = dict(enumerate(procs))
        while pending:
            for rank, p in list(pending.items()):
                rc = p.poll()
                if rc is None:
                    continue
                del pending[rank]
                if rc != 0:
                    errs.append(rank)
            if errs:
                # fail fast: peers would otherwise block in rendezvous or a
                # collective forever, waiting for the dead worker
                for p in pending.values():
                    p.kill()
                raise RuntimeError(f"worker(s) {errs} exited non-zero; "
                                   "remaining workers killed")
            if pending and time.monotonic() > deadline:
                for p in pending.values():
                    p.kill()
                raise TimeoutError(
                    f"worker(s) {sorted(pending)} still running after "
                    f"{timeout}s; killed")
            if pending:
                time.sleep(0.2)
    finally:
        try:
            os.unlink(fn_path)
        except OSError:
            pass
