"""Fleet replica process: ``python -m xgboost_tpu.serving.replica``.

One replica = one OS process running a :class:`ServingEngine` over the
fleet's shared resources (docs/serving.md "Fleet"):

- models come out of the mmap :class:`ModelStore` (one host copy fleet-
  wide, zero-copy into XLA on CPU);
- serve programs come out of the :class:`WarmProgramCache` AOT warm file
  plus the XLA persistent compilation cache, so a warm-cache replica is
  ready in milliseconds of warm work instead of seconds of compiles;
- requests arrive as wire frames (raw f32 or Arrow IPC, decoded zero-copy
  at the kernel boundary) over ONE dispatcher connection with at most one
  frame in flight — batching happened upstream, so the engine runs
  batcher-less and every predict is a direct inline execute.

Protocol: connect to the dispatcher, send ``hello``, warm, send ``ready``
(carrying the measured warm-work seconds + AOT hit/compile counts — the
cold-start telemetry BENCH_SERVE.json persists), then serve ``predict``
frames until ``close``/EOF.  Any uncaught error is fatal by design: the
dispatcher owns the retry/respawn policy (launcher WorkerFailedError
machinery), a wounded replica must die loudly, not limp.

Lifecycle control ops (docs/serving.md "Online model lifecycle") ride the
same serialized connection as predicts, which is what gives hot-swap its
drain semantics for free: by the time the replica processes an
``activate`` or ``retire`` frame, every predict the dispatcher sent before
it has already completed.

- ``load`` — mmap a published version out of the store and double-buffer
  it NEXT TO the incumbent: registry entry, AOT programs (arch-keyed warm
  cache: a same-architecture continuation deserializes instead of
  compiling), fast path, and a NaN warm pass, all while the incumbent
  keeps serving.
- ``activate`` — repoint unversioned requests at a loaded version (pin +
  fast-path alias flip: one dict store, no request ever sees a half-swap).
  Self-sufficient: a respawned replica that missed the ``load`` broadcast
  loads here.
- ``retire`` — drop a non-active version.  Runs through
  ``registry.remove``, whose retirement hook also fires on LRU eviction —
  one cleanup path for both causes.

At startup the replica serves the store's ACTIVE version per model (the
manifest's committed serving version, falling back to latest) and pins it,
so capacity pressure from candidate loads can only evict old candidates,
never what is live.
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time

import numpy as np


class _FastPath:
    """Per-snapshot lean predict: numpy scratch -> AOT executable, no
    engine machinery on the wire hot path.

    ``engine.predict`` costs ~0.5ms of registry/validation/metrics/jax-
    dispatch Python per request; handing a padded numpy scratch straight
    to the AOT executable (the C++ dispatch path converts it) runs the
    same program for ~0.3ms — bitwise the same result (one fused
    executable serves both polarities).  The scratch is reusable
    immediately: the call copies the input to a device buffer before
    returning, and the serve loop is serial.  Anything the fast path
    cannot take (no AOT program for the bucket, feature-count mismatch,
    stump models) falls back to the engine, which owns validation and
    error text.
    """

    def __init__(self, snap) -> None:
        self.snap = snap
        self._scratch: dict = {}  # bucket -> padded (B, F) numpy buffer

    def run(self, X: np.ndarray, output_margin: bool):
        snap = self.snap
        if (X.ndim != 2 or X.dtype != np.float32
                or X.shape[1] != snap.num_features):
            return None
        R = int(X.shape[0])
        from ..ops.predict import bucket_rows

        bucket = bucket_rows(R)
        prog = snap.aot_programs.get(bucket)
        if prog is None:
            return None
        if bucket == R:
            Xp = X
        else:
            Xp = self._scratch.get(bucket)
            if Xp is None:
                Xp = np.full((bucket, max(snap.num_features, 1)), np.nan,
                             np.float32)
                self._scratch[bucket] = Xp
            Xp[:R] = X
            Xp[R:] = np.nan  # previous request's tail rows must not leak
        host = np.asarray(snap.aot_execute(Xp, output_margin))
        out = host[:R] if bucket != R else host
        return out[:, 0] if out.shape[1] == 1 else out


def _warm_fastpath(engine, fp, name, version, buckets) -> None:
    """One NaN-row execute per bucket through the steady-state path (see
    the startup warm loop) so the first real request after a load/activate
    runs at steady-state latency."""
    snap = fp.snap
    for b in buckets:
        X = np.full((int(b), max(snap.num_features, 1)), np.nan, np.float32)
        if fp.run(X, False) is None:
            engine.predict(name, X, direct=True, version=version)


def _apply_control(engine, store, warm, fast, buckets, header) -> dict:
    """One lifecycle control op (load / activate / retire); returns the
    ack payload.  Raises on a bad request — the serve loop reports it as a
    typed per-request error and keeps serving."""
    import time

    op = header["op"]
    name = header["model"]
    version = int(header["version"])
    t0 = time.perf_counter()
    if op == "retire":
        fp = fast.get((name, version))
        if fp is not None and fast.get((name, None)) is fp:
            raise ValueError(
                f"cannot retire the active version {name!r} v{version}; "
                "activate another version first")
        # registry.remove fires the retirement hook, which drops the
        # (name, version) fast-path entry — the same path LRU eviction runs
        engine.registry.remove(name, version)
        return {"seconds": time.perf_counter() - t0}
    st = {"hits": 0, "compiled": 0}
    fp = fast.get((name, version))
    if fp is None:
        # attach gate: re-verify the arena checksum BEFORE anything maps
        # it into serving — a corrupt candidate is refused as a typed
        # per-request error (incumbent untouched), never loaded
        _verify_arena(store, name, version)
        # double-buffer: the incumbent's registry entry, AOT programs, and
        # fast path all stay live while the candidate builds next to them
        snap = store.snapshot(name, version)
        engine.registry.register_snapshot(name, snap, version)
        st = warm.attach(snap, buckets)
        fp = _FastPath(snap)
        fast[(name, version)] = fp
        _warm_fastpath(engine, fp, name, version, buckets)
        warm.save()
    if op == "activate":
        # pin: get(name) resolves here and capacity pressure cannot evict
        # it; the alias flip is one dict store, so every request sees
        # either the old fast path or the new one, never neither
        engine.registry.pin(name, version)
        fast[(name, None)] = fp
    return {"aot_hits": st["hits"], "aot_compiled": st["compiled"],
            "seconds": time.perf_counter() - t0}


def _verify_arena(store, name, version) -> None:
    """Raise :class:`~xgboost_tpu.serving.modelstore.ArenaCorruptError`
    when the (mmapped) arena no longer matches its publish-time checksum.
    On the CPU backend the mmap pages ARE the served bytes (zero-copy
    aliasing), so this re-derivation verifies exactly what predictions
    read."""
    from .modelstore import ArenaCorruptError

    if not store.verify_checksum(name, version):
        raise ArenaCorruptError(
            f"arena checksum diverged for {name!r} v{version}: refusing "
            "to serve corrupted model bytes")


def _scrub_resident(store, fast: dict) -> int:
    """Re-verify every RESIDENT version against the store meta; returns
    the number verified, raises ``ArenaCorruptError`` on the first
    divergence (the serve loop turns that into quarantine-and-die)."""
    resident = sorted({(n, v) for (n, v) in fast if v is not None})
    for name, version in resident:
        _verify_arena(store, name, version)
    return len(resident)


def _scrub_interval() -> float:
    """Periodic arena-scrub tick, seconds (0 disables).  Piggybacks on the
    serve loop like telemetry shipping, so an idle replica scrubs at its
    next frame — traffic is what makes corruption matter."""
    try:
        return float(os.environ.get("XGBOOST_TPU_ARENA_SCRUB_S", "300"))
    except ValueError:
        return 300.0


def ship_telemetry(sock, label: str) -> bool:
    """One ``op="telemetry"`` frame on the dispatcher connection: the full
    registry snapshot + flight-recorder ring (JSON payload, header-only
    routing like every fleet frame).  Best-effort — shipping must never
    take the serve loop down."""
    from ..telemetry import distributed

    try:
        payload = json.dumps(distributed.snapshot_payload()).encode()
        from . import wire

        wire.send_frame(sock, {"op": wire.TELEMETRY, "label": label},
                        payload)
        return True
    except OSError as e:
        from ..reliability import resources as _resources

        _resources.note_os_error(e, "replica.ship")
        return False
    except (TypeError, ValueError):
        return False


def _sampled(trace, every: int) -> bool:
    """Deterministic 1-in-N feedback selection, keyed off the request-id
    half of the dispatcher's trace id (``{pid:x}-{rid:x}``).  The rid
    counter — never the pid half, never a PRNG — so a seeded replay of the
    same request schedule samples the same requests whatever pid the
    dispatcher process drew (docs/online.md "Determinism contract")."""
    try:
        rid = int(str(trace).split("-")[1], 16)
    except (IndexError, ValueError):
        return False
    return rid % every == 0


def _capture_feedback(sock, header, X, out) -> None:
    """Ship one sampled request back to the dispatcher (``op="feedback"``):
    payload = the feature rows' raw f32 bytes followed by the served
    scores' raw f32 bytes.  Best-effort like :func:`ship_telemetry` — the
    result frame already went out, so a failed capture must drop the
    sample (counted driver-side as a join shortfall), never the request
    or the serve loop.  The ``online.sample`` seam is the loop's
    capture-side fault point: an injected exception is exactly a dropped
    sample."""
    from ..reliability import faults as _faults
    from ..telemetry import flight
    from . import wire

    try:
        _faults.maybe_inject("online.sample")
        Xc = np.ascontiguousarray(X, np.float32)
        oc = np.ascontiguousarray(out, np.float32)
        wire.send_frame(sock, {"op": wire.FEEDBACK,
                               "model": header["model"],
                               "trace": header.get("trace"),
                               "shape": list(Xc.shape),
                               "oshape": list(oc.shape)},
                        Xc.tobytes() + oc.tobytes())
    except _faults.FaultInjected as e:
        flight.record("fault", "online.sample", error=str(e))
    except OSError as e:
        from ..reliability import resources as _resources

        _resources.note_os_error(e, "online.sample_ship")


def _replica_stall(op) -> None:
    """Watchdog stall stage for a wedged request: die loudly.  The stack
    dump already landed at the dump stage; the dispatcher's death path
    reroutes the in-flight batch and respawns — a stalled replica
    becomes a dead one, which the fleet already survives."""
    from ..telemetry import flight

    flight.record("fault", "replica.stall", **op.detail)
    try:
        flight.dump()
    except OSError as e:
        from ..reliability import resources as _resources

        _resources.note_os_error(e, "replica.flight_dump")
    os._exit(121)


def _serve_loop(sock, engine, fast: dict, store=None, warm=None,
                buckets=(), label: str = "replica") -> None:
    from . import wire
    from ..reliability import watchdog
    from ..telemetry import distributed, flight, trace
    from ..telemetry.registry import get_registry

    # fast-path requests bypass the engine (and its ServingMetrics), so
    # the serve loop feeds the per-model counters itself — same families
    # the engine registered, so get-or-create just hands them back
    reg = get_registry()
    req_counter = reg.counter("xtb_serve_requests_total",
                              "predict requests", ("model",))
    rows_counter = reg.counter("xtb_serve_rows_total", "rows predicted",
                               ("model",))
    # telemetry shipping piggybacks on traffic (no background sender: the
    # socket is single-writer by design).  An idle replica ships nothing —
    # and needs to: with no requests handled, its counters haven't moved,
    # so the dispatcher's retained snapshot is still exact.
    from .modelstore import ArenaCorruptError

    def _quarantine(e: BaseException, rid=None) -> None:
        # the replica's own loaded checksum diverged: tell the dispatcher
        # WHY before dying loudly (it fences the label, reroutes the
        # in-flight batch, and decides respawn) — then die; a wounded
        # replica must never keep serving.  The quarantine COUNTER is the
        # dispatcher's (on frame receipt): counting here too would
        # double the merged view once this replica's final telemetry
        # ship lands driver-side.
        flight.record("fault", "replica.quarantine", error=str(e))
        try:
            wire.send_frame(sock, {"op": "quarantine", "id": rid,
                                   "label": label, "error": str(e)})
        except OSError as se:
            from ..reliability import resources as _resources

            _resources.note_os_error(se, "replica.quarantine_send")

    interval = distributed.ship_interval()
    scrub_s = _scrub_interval()
    # feedback-capture config per model (the "sample" control broadcast):
    # model -> every-N; 0/absent = capture off (the default, so serving
    # pays nothing until the online loop turns it on)
    sample: dict = {}
    last_ship = last_scrub = time.monotonic()
    # native rx path when available (xtb_wire.cc): ONE GIL release
    # covers the whole frame read + CRC; pure-Python reader otherwise
    stream = wire.reader(sock)
    while True:
        try:
            # peer=label lets fault plans shape this direction of the
            # link independently (blackhole_rx / partition on the
            # replica's inbound side); no budget_s — the dispatcher is
            # the trusted side, and an idle dispatcher is not a stall
            header, payload = wire.recv_frame(stream, peer=label)
        except wire.WireCorruptError:
            # corrupted frame: this connection cannot be trusted at any
            # subsequent byte — quarantine it (exit; the dispatcher's
            # death path reroutes and respawns), never decode garbage
            from ..reliability import integrity as _integrity

            _integrity.quarantined("wire")
            flight.record("fault", "replica.wire_corrupt")
            return
        except wire.WireError:
            return  # dispatcher gone: clean exit
        op = header.get("op")
        rid = header.get("id")
        if op == "close":
            return
        if op == wire.PING:
            # heartbeat: answer immediately, before the watchdog guard —
            # the serialized connection already proves ordering, and a
            # pong queued behind a long predict still lands within the
            # dispatcher's liveness deadline while a half-open link never
            # answers at all
            wire.send_frame(sock, {"op": wire.PONG,
                                   "seq": header.get("seq"),
                                   "label": label})
            continue
        # liveness marker (ships with every telemetry frame) + a per-
        # request watchdog: a frame whose handling wedges past the budget
        # gets an all-thread stack dump and then a LOUD death, steering
        # recovery into the dispatcher's existing reroute/respawn path.
        # Idle recv is not guarded — no traffic is not a stall.
        watchdog.progress("replica.request", id=rid, op=op)
        with watchdog.guard("replica.execute", op=op, id=rid,
                            on_stall=_replica_stall):
            if op == "scrub":
                try:
                    n = _scrub_resident(store, fast)
                    wire.send_frame(sock, {"op": "ctrl_ok", "id": rid,
                                           "verified": n})
                except ArenaCorruptError as e:
                    _quarantine(e, rid)
                    raise
                continue
            if op == "sample":
                # feedback-capture control: set/clear the per-model 1-in-N
                # rate.  Rides the serialized connection like every
                # lifecycle op — requests dispatched before this frame are
                # sampled (or not) under the previous rate, deterministically
                every = int(header.get("every", 0) or 0)
                if every > 0:
                    sample[header["model"]] = every
                else:
                    sample.pop(header["model"], None)
                flight.record("event", "replica.sample",
                              model=header.get("model"), every=every,
                              trace=header.get("trace"))
                wire.send_frame(sock, {"op": "ctrl_ok", "id": rid,
                                       "every": every})
                continue
            if op in ("load", "activate", "retire"):
                try:
                    ack = _apply_control(engine, store, warm, fast, buckets,
                                         header)
                    ack.update({"op": "ctrl_ok", "id": rid})
                    flight.record("event", f"replica.{op}",
                                  model=header.get("model"),
                                  version=header.get("version"),
                                  trace=header.get("trace"))
                    wire.send_frame(sock, ack)
                except Exception as e:  # report, keep serving
                    flight.record("fault", f"replica.{op}", error=str(e))
                    wire.send_frame(sock, {"op": "error", "id": rid,
                                           "etype": type(e).__name__,
                                           "error": str(e)})
            elif op != "predict":
                wire.send_frame(sock, {"op": "error", "id": rid,
                                       "etype": "ValueError",
                                       "error": f"unknown op {op!r}"})
            else:
                t0 = time.perf_counter_ns()
                try:
                    X = wire.decode_matrix(header, payload)
                    margin = bool(header.get("margin", False))
                    fp = fast.get((header["model"], header.get("version")))
                    out = fp.run(X, margin) if fp is not None else None
                    if out is not None:
                        req_counter.labels(header["model"]).inc()
                        rows_counter.labels(header["model"]).inc(
                            float(X.shape[0]))
                    else:
                        out = engine.predict(header["model"], X,
                                             direct=True,
                                             version=header.get("version"),
                                             output_margin=margin)
                    out = np.ascontiguousarray(out, np.float32)
                    wire.send_frame(sock, {"op": "result", "id": rid,
                                           "shape": list(out.shape)},
                                    memoryview(out).cast("B"))
                    if trace.active() and header.get("trace"):
                        # same trace id the dispatcher stamped at submit:
                        # the merged capture pairs this bracket with
                        # fleet.queue/fleet.request from the driver
                        trace.emit("replica.execute", t0,
                                   time.perf_counter_ns() - t0,
                                   trace=header["trace"],
                                   model=header.get("model"),
                                   rows=int(out.shape[0]))
                    # feedback capture AFTER the result frame: only
                    # unversioned (live-traffic) requests — explicit-
                    # version probes, shadow twins, and hedge twins are
                    # measurements/duplicates, not traffic the window
                    # should learn from (a hedge pair sampled twice would
                    # double-weight one request)
                    ev = sample.get(header["model"], 0)
                    if (ev > 0 and header.get("version") is None
                            and not header.get("hedge")
                            and _sampled(header.get("trace"), ev)):
                        _capture_feedback(sock, header, X, out)
                except Exception as e:  # per-request failure: serve on
                    flight.record("fault", "replica.predict",
                                  model=header.get("model"), error=str(e))
                    wire.send_frame(sock, {"op": "error", "id": rid,
                                           "etype": type(e).__name__,
                                           "error": str(e)})
        now = time.monotonic()
        if now - last_ship >= interval:
            last_ship = now
            ship_telemetry(sock, label)
        if scrub_s > 0 and now - last_scrub >= scrub_s:
            # periodic scrub tick (piggybacked like telemetry shipping):
            # a replica whose loaded checksum diverged quarantines itself
            last_scrub = now
            try:
                _scrub_resident(store, fast)
            except ArenaCorruptError as e:
                _quarantine(e)
                raise


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="xgboost_tpu fleet replica")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--store", required=True)
    ap.add_argument("--cache", default="")
    ap.add_argument("--label", default="replica0")
    ap.add_argument("--nthread", type=int, default=0)
    ap.add_argument("--platform", default="")
    ap.add_argument("--buckets", default="",
                    help="comma-separated warm row buckets ('' = engine "
                         "default ladder)")
    args = ap.parse_args(argv)

    from ..telemetry import flight, profiler, trace

    flight.install(args.label)
    # default-on wall sampler: replica execute loops ship folded stacks
    # with every telemetry frame into the driver's merged flame view
    profiler.maybe_start(args.label)
    flight.record("event", "replica.start", label=args.label,
                  pid=os.getpid())
    if trace.active():
        trace.set_process_name(f"replica:{args.label}")

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    if args.cache:
        from .warmcache import configure_persistent_cache

        configure_persistent_cache(args.cache)
    from ..utils import native

    if args.nthread > 0:
        native.set_nthread(args.nthread)

    from . import wire
    from .engine import ServeConfig, ServingEngine
    from .modelstore import ModelStore
    from .warmcache import WarmProgramCache

    sock = wire.configure(
        socket.create_connection((args.host, args.port), timeout=30))
    sock.settimeout(None)
    wire.send_frame(sock, {"op": "hello", "label": args.label,
                           "pid": os.getpid()})

    # process bring-up, identical whatever the cache state: PJRT backend
    # client, native FFI library, jit dispatch machinery.  Timed apart from
    # warmup_s so the cold-start telemetry isolates CACHE-dependent work
    # (compile vs deserialize/disk-hit) from fixed per-process costs.
    t_up = time.perf_counter()
    import jax.numpy as jnp

    jnp.add(jnp.zeros(1, jnp.float32), 1.0).block_until_ready()
    native.load_ffi()
    bringup_s = time.perf_counter() - t_up

    t0 = time.perf_counter()
    store = ModelStore(args.store)
    entries = store.serving_entries()  # the committed ACTIVE version each
    cfg = ServeConfig(use_batcher=False,
                      max_models=max(8, len(entries) + 2))
    engine = ServingEngine(cfg)

    if args.buckets:
        buckets = tuple(int(b) for b in args.buckets.split(",") if b)
    else:
        buckets = cfg.resolved_warmup_buckets()
    warm = WarmProgramCache(args.cache or None)
    n_hits = n_compiled = 0
    fast: dict = {}

    def _drop_fast(name, version, reason, snap):
        # registry retirement hook: LRU eviction and lifecycle retire()
        # both land here, so per-version fast-path state can never outlive
        # residency whatever caused the exit (the active alias is safe: the
        # active version is pinned, and retire refuses it explicitly)
        fast.pop((name, version), None)

    engine.registry.add_retire_hook(_drop_fast)

    for name, version in entries:
        # attach gate: a corrupt store entry must fail replica startup
        # LOUDLY (launcher failure with the cause in the stderr tail) —
        # never serve bytes the publish-time checksum disowns
        _verify_arena(store, name, version)
        snap = store.snapshot(name, version)
        engine.registry.register_snapshot(name, snap, version)
        st = warm.attach(snap, buckets)
        fp = _FastPath(snap)
        # the store's active version answers unversioned requests; pinned
        # so candidate loads can never evict what is live
        fast[(name, version)] = fast[(name, None)] = fp
        engine.registry.pin(name, version)
        n_hits += st["hits"]
        n_compiled += st["compiled"]
        # one NaN-row execute per bucket through the STEADY-STATE path
        # (the fast path: numpy scratch -> AOT call): pages the arena in,
        # allocates the scratch, runs the program — READY means the first
        # real request runs at steady-state latency.  Buckets the AOT
        # layer doesn't cover (stump models) warm via the engine instead;
        # an engine-fallback request for an odd shape pays its own lazy
        # compile, same as any unwarmed bucket.
        _warm_fastpath(engine, fp, name, version, buckets)
    warm.save()
    warmup_s = time.perf_counter() - t0
    wire.send_frame(sock, {
        "op": "ready", "label": args.label, "warmup_s": warmup_s,
        "bringup_s": bringup_s, "models": len(entries), "aot_hits": n_hits,
        "aot_compiled": n_compiled,
        "cache_state": ("warm" if n_hits and not n_compiled
                        else "partial" if n_hits else "cold"),
        "backend": jax.default_backend(),
        # sharded fleets prefix labels with "s{k}:" — surfacing the
        # shard here lets replica_info() rows identify their owner
        "shard": (args.label.split(":", 1)[0]
                  if ":" in args.label else ""),
    })

    ship_telemetry(sock, args.label)  # baseline snapshot before traffic
    try:
        _serve_loop(sock, engine, fast, store=store, warm=warm,
                    buckets=buckets, label=args.label)
    except BaseException as e:
        # wounded replicas die loudly — but first leave a postmortem: an
        # all-thread stack dump plus the local flight dump; the
        # finally-ship below carries the ring (with this crash fault) to
        # the driver too
        flight.record("fault", "replica.crash", error=repr(e))
        flight.dump_stacks()
        try:
            flight.dump()
        except OSError as de:
            from ..reliability import resources as _resources

            _resources.note_os_error(de, "replica.flight_dump")
        raise
    finally:
        ship_telemetry(sock, args.label)  # final counters survive us
        engine.close()
        try:
            sock.close()
        except OSError as ce:
            from ..reliability import resources as _resources

            _resources.note_os_error(ce, "replica.sock_close")
    return 0


if __name__ == "__main__":
    sys.exit(main())
