"""Model registry: versioned snapshots with LRU residency and pinning.

The hot-model residency policy of arXiv:1603.02754's cache-conscious design
applied at model granularity: at most ``max_models`` snapshots keep their
stacked tree tensors device-resident; the least-recently-served unpinned
entry is evicted when a new model loads.  Versions are monotonically
numbered per name; ``pin`` freezes the version ``get`` resolves to (the
rollout/rollback knob) and pinned entries are never evicted.

Every exit from residency — LRU eviction for capacity AND explicit
``remove()`` (lifecycle version retirement) — funnels through ONE path:
``xtb_serve_evicted_total{model,reason}`` counts it and registered
retirement hooks (:meth:`ModelRegistry.add_retire_hook`) fire, so a fleet
replica drops its per-version fast-path state identically whether the
registry aged a model out or the lifecycle manager retired it.
"""
from __future__ import annotations

import os
import threading
import warnings
from typing import Callable, Dict, List, Optional, Tuple

from .snapshot import InferenceSnapshot

_evicted = None  # xtb_serve_evicted_total family, created lazily


def _evicted_counter():
    global _evicted
    if _evicted is None:
        from ..telemetry.registry import get_registry

        _evicted = get_registry().counter(
            "xtb_serve_evicted_total",
            "snapshots leaving registry residency, by cause "
            "(lru = capacity eviction, retired = explicit remove)",
            ("model", "reason"))
    return _evicted


class _Entry:
    __slots__ = ("snapshot", "pinned", "tick")

    def __init__(self, snapshot: InferenceSnapshot) -> None:
        self.snapshot = snapshot
        self.pinned = False
        self.tick = 0


def _load_booster(source):
    """Booster passthrough, or load from a JSON/UBJSON model file."""
    from ..core import Booster

    if isinstance(source, Booster):
        return source
    if isinstance(source, (str, os.PathLike)):
        return Booster(model_file=os.fspath(source))
    raise TypeError(
        f"model source must be a Booster or a .json/.ubj path, got "
        f"{type(source).__name__}")


class ModelRegistry:
    def __init__(self, max_models: int = 8) -> None:
        if max_models < 1:
            raise ValueError("max_models must be >= 1")
        self.max_models = int(max_models)
        self._lock = threading.RLock()
        self._entries: Dict[Tuple[str, int], _Entry] = {}
        self._latest: Dict[str, int] = {}
        self._pinned_version: Dict[str, int] = {}
        self._retire_hooks: List[Callable] = []
        self._clock = 0
        self.evictions = 0

    # ----------------------------------------------------------------- util
    def _touch(self, e: _Entry) -> None:
        self._clock += 1
        e.tick = self._clock

    def add_retire_hook(self, fn: Callable[[str, int, str, InferenceSnapshot],
                                           None]) -> None:
        """Register ``fn(name, version, reason, snapshot)`` to fire whenever
        a snapshot leaves residency (``reason`` = ``"lru"`` / ``"retired"``).
        Hooks run under the registry lock (RLock: re-entrant registry calls
        are fine) and must be cheap and non-blocking."""
        with self._lock:
            self._retire_hooks.append(fn)

    def _retire_entry(self, key: Tuple[str, int], entry: _Entry,
                      reason: str) -> None:
        """The ONE exit path from residency (caller holds the lock): count
        it, fire the retirement hooks, keep get(name) resolving to the
        highest surviving version."""
        del self._entries[key]
        name, version = key
        _evicted_counter().labels(name, reason).inc()
        for fn in self._retire_hooks:
            try:
                fn(name, version, reason, entry.snapshot)
            except Exception as e:  # a broken hook must not corrupt residency
                warnings.warn(f"registry retire hook failed for "
                              f"{key}: {e!r}", RuntimeWarning, stacklevel=3)
        # retiring the latest version must not orphan still-resident older
        # ones: keep get(name) resolving to the highest surviving version
        if self._latest.get(name) == version:
            remaining = [v for n, v in self._entries if n == name]
            if remaining:
                self._latest[name] = max(remaining)
            else:
                self._latest.pop(name, None)

    def _evict_for_capacity(self) -> None:
        while len(self._entries) >= self.max_models:
            victims = [(e.tick, key) for key, e in self._entries.items()
                       if not e.pinned]
            if not victims:
                raise RuntimeError(
                    f"registry full ({self.max_models} models, all pinned); "
                    "unpin or raise ServeConfig.max_models")
            _, key = min(victims)
            self.evictions += 1
            self._retire_entry(key, self._entries[key], "lru")

    # ------------------------------------------------------------------ API
    def register(self, name: str, source, version: Optional[int] = None,
                 ) -> int:
        """Snapshot ``source`` (Booster or model path) under ``name``.
        Returns the version number (auto-incremented when not given)."""
        # replacing a pinned version keeps the pin (the replacement is
        # what get() now resolves to; it must not become LRU-evictable)
        return self.register_snapshot(
            name, InferenceSnapshot.from_booster(_load_booster(source)),
            version)

    def register_snapshot(self, name: str, snap: InferenceSnapshot,
                          version: Optional[int] = None) -> int:
        """Register an already-built snapshot (the fleet replica path:
        snapshots come out of the mmap ModelStore, not a Booster)."""
        with self._lock:
            if version is None:
                version = self._latest.get(name, 0) + 1
            version = int(version)
            if (name, version) not in self._entries:
                self._evict_for_capacity()
            e = _Entry(snap)
            e.pinned = self._pinned_version.get(name) == version
            self._entries[(name, version)] = e
            self._latest[name] = max(self._latest.get(name, 0), version)
            self._touch(e)
            return version

    def get(self, name: str, version: Optional[int] = None,
            ) -> Tuple[InferenceSnapshot, int]:
        with self._lock:
            if version is None:
                version = self._pinned_version.get(
                    name, self._latest.get(name))
            if version is None:
                raise KeyError(f"unknown model {name!r}")
            e = self._entries.get((name, int(version)))
            if e is None:
                raise KeyError(
                    f"model {name!r} version {version} is not resident "
                    "(never registered, or LRU-evicted); re-register it")
            self._touch(e)
            return e.snapshot, int(version)

    def pin(self, name: str, version: int) -> None:
        """Resolve ``get(name)`` to ``version`` and shield it from eviction."""
        with self._lock:
            key = (name, int(version))
            if key not in self._entries:
                raise KeyError(f"cannot pin absent model {key}")
            # at most one pinned version per name
            old = self._pinned_version.get(name)
            if old is not None and (name, old) in self._entries:
                self._entries[(name, old)].pinned = False
            self._pinned_version[name] = int(version)
            self._entries[key].pinned = True

    def unpin(self, name: str) -> None:
        with self._lock:
            v = self._pinned_version.pop(name, None)
            if v is not None and (name, v) in self._entries:
                self._entries[(name, v)].pinned = False

    def remove(self, name: str, version: Optional[int] = None) -> None:
        with self._lock:
            keys = [k for k in self._entries
                    if k[0] == name and (version is None or k[1] == version)]
            for k in keys:
                # same single exit path as LRU eviction: the retirement
                # hooks + counter fire identically for a lifecycle retire
                self._retire_entry(k, self._entries[k], "retired")
            if version is None or self._pinned_version.get(name) == version:
                self._pinned_version.pop(name, None)

    def names(self) -> List[str]:
        with self._lock:
            return sorted({k[0] for k in self._entries})

    def versions(self, name: str) -> List[int]:
        with self._lock:
            return sorted(v for n, v in self._entries if n == name)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.snapshot.nbytes for e in self._entries.values())

    def serve_programs(self) -> list:
        """The _Program wrappers riding resident snapshots (engine-owned;
        exposed so the engine can fold their donated-jit caches into its
        compile gauge)."""
        with self._lock:
            progs = [getattr(e.snapshot, "_serve_prog", None)
                     for e in self._entries.values()]
        return [p for p in progs if p is not None]
