"""Replica warm-start: persistent XLA compile cache + AOT program file.

Replica cold-start has two compile layers, attacked separately:

1. **XLA persistent compilation cache** (:func:`configure_persistent_cache`)
   — ``jax_compilation_cache_dir`` pointed at the fleet cache directory, so
   every backend compile (peripheral eager ops, transforms, anything not
   AOT-covered) is a disk hit after the first replica ever ran.  This layer
   skips *compilation* but still pays trace + lowering per program.

2. **AOT program warm file** (``programs.pkl``) — the serving margin
   programs themselves (the multi-second part of warm-up) are compiled
   once, serialized with ``jax.experimental.serialize_executable``, and
   deserialized by every later replica: no trace, no lowering, no compile
   — a few ms per program.  This is what turns replica cold-start from
   seconds into milliseconds (BENCH_SERVE.json ``fleet_coldstart``).

The serialized program is a *fused serve step*: bucket-padded rows in,
``(margin + base_score, pred_transform(margin + base_score))`` out — one
executable serves both ``output_margin`` polarities, and the warm path
never traces the peripheral add/transform ops either.  Programs are keyed
by everything that shapes the executable (stacked tensor shapes/dtypes,
depth, group count, objective, bucket, jax/backend version), NOT by the
weights: two same-architecture model versions share one program, so a
hot-swapped retrain warms instantly.

Executables embed the ``xtb_predict`` FFI custom call; deserialization
requires the native library's targets registered first —
:func:`attach_aot` handles that ordering.  The warm file is advisory: any
load failure (version skew, corrupt file) falls back to a fresh compile
and rewrites the file (atomic tmp + rename).
"""
from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

_WARM_FILE = "programs.pkl"
_FORMAT = 1


def configure_persistent_cache(cache_dir: str) -> None:
    """Point jax's persistent compilation cache at ``cache_dir`` (idempotent;
    call before the first jit of the process for full effect)."""
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", os.fspath(cache_dir))
    # serving programs are small and fast to compile individually — cache
    # all of them, not just the slow ones
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except AttributeError:  # knob added in jax 0.4.30; older = size 0 floor
        pass


def program_key(snap, bucket: int) -> str:
    """Cache key for one (snapshot architecture, row bucket) serve program.

    Hashes program *shape*, never weights — see module docstring.  The jax
    and backend versions are folded in because serialized executables are
    not portable across them.
    """
    import jax

    h = hashlib.sha256()
    h.update(f"fmt{_FORMAT}|jax{jax.__version__}|"
             f"{jax.default_backend()}|".encode())
    h.update(f"b{int(bucket)}|d{snap.depth}|g{snap.n_groups}|"
             f"f{snap.num_features}|{type(snap.objective).__name__}|"
             f"{getattr(snap, 'store_meta', {}).get('objective', '')}|"
             .encode())
    if snap.stacked is None:
        h.update(b"stump")
    else:
        for k in sorted(snap.stacked):
            v = snap.stacked[k]
            if v is None:
                h.update(f"{k}:none|".encode())
            else:
                h.update(f"{k}:{tuple(v.shape)}:{np.dtype(v.dtype).str}|"
                         .encode())
    return h.hexdigest()


def _fused_serve_fn(snap):
    """The traced serve step for one snapshot: padded rows -> (margin,
    transformed), base score folded in.  Bitwise-identical math to the
    engine's eager path (same run_stacked_margin trace, same elementwise
    add/transform — fusion cannot reassociate per-element chains)."""
    from ..ops.predict import run_stacked_margin

    depth, n_groups, objective = snap.depth, snap.n_groups, snap.objective

    def fn(Xp, stacked, groups, base):
        m = run_stacked_margin(Xp, stacked, groups, depth, n_groups,
                               None) + base[None, :]
        return m, objective.pred_transform(m)

    return fn


def build_program(snap, bucket: int):
    """Trace + lower + compile the fused serve program for one bucket."""
    import jax
    import jax.numpy as jnp

    fn = _fused_serve_fn(snap)
    Xp = jax.ShapeDtypeStruct((int(bucket), max(snap.num_features, 1)),
                              jnp.float32)
    base = jax.ShapeDtypeStruct((snap.n_groups,), jnp.float32)
    shaped = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), dict(snap.stacked))
    groups = (jax.ShapeDtypeStruct(snap.groups.shape, snap.groups.dtype)
              if snap.groups is not None else None)
    return jax.jit(fn).lower(Xp, shaped, groups, base).compile()


class WarmProgramCache:
    """The ``programs.pkl`` warm file in a fleet cache directory.

    ``attach(snap, buckets)`` populates ``snap.aot_programs`` (bucket ->
    compiled executable), deserializing warm entries and compiling+
    collecting cold ones; ``save()`` persists anything newly compiled.
    Thread-safe for the multi-model replica warm loop.
    """

    def __init__(self, cache_dir: Optional[str]) -> None:
        self.dir = os.fspath(cache_dir) if cache_dir else None
        self._lock = threading.Lock()
        self._payloads: Dict[str, tuple] = {}
        self._dirty = False
        self.hits = 0
        self.misses = 0
        if self.dir:
            os.makedirs(self.dir, exist_ok=True)
            self._payloads = self._load_file()

    def _path(self) -> str:
        return os.path.join(self.dir, _WARM_FILE)

    def _load_file(self) -> Dict[str, tuple]:
        try:
            with open(self._path(), "rb") as fh:
                obj = pickle.load(fh)
            if obj.get("format") == _FORMAT:
                return dict(obj["programs"])
        except FileNotFoundError:
            pass  # no warm file yet: every program compiles (cold)
        except OSError as e:
            from ..reliability import resources as _resources

            _resources.note_os_error(e, "warmcache.load")
        except (pickle.UnpicklingError, EOFError, KeyError,
                AttributeError):
            pass  # stale/corrupt cache payload: fall back to compiling
        return {}

    # ------------------------------------------------------------------ API
    def attach(self, snap, buckets) -> dict:
        """Ensure ``snap.aot_programs[bucket]`` exists for every bucket.
        Returns ``{"hits": n, "compiled": n, "seconds": s}``."""
        from ..utils import native
        from jax.experimental import serialize_executable

        t0 = time.perf_counter()
        stats = {"hits": 0, "compiled": 0, "seconds": 0.0}
        if snap.stacked is None:  # stump: nothing worth AOT-ing
            return stats
        native.load_ffi()  # register custom-call targets BEFORE deserialize
        for bucket in sorted({int(b) for b in buckets}):
            if bucket in snap.aot_programs:
                continue
            key = program_key(snap, bucket)
            with self._lock:
                payload = self._payloads.get(key)
            compiled = None
            if payload is not None:
                try:
                    compiled = serialize_executable.deserialize_and_load(
                        *payload)
                    stats["hits"] += 1
                except Exception:
                    compiled = None  # stale/foreign entry: recompile below
            if compiled is None:
                compiled = build_program(snap, bucket)
                stats["compiled"] += 1
                if self.dir:
                    ser = serialize_executable.serialize(compiled)
                    # an executable that build_program got as an XLA
                    # persistent-cache HIT serializes non-hermetically
                    # (deserialize dies with "Symbols not found" — the
                    # cached artifact lacks the JIT'd function bodies).
                    # The round-trip check catches exactly that in-process;
                    # a payload that fails it must never reach the warm
                    # file.  Whoever actually COMPILED the program
                    # persists a good entry, so the fleet still converges.
                    try:
                        serialize_executable.deserialize_and_load(*ser)
                    except Exception:
                        ser = None
                    if ser is not None:
                        with self._lock:
                            self._payloads[key] = ser
                            self._dirty = True
            snap.aot_programs[bucket] = compiled
        with self._lock:
            self.hits += stats["hits"]
            self.misses += stats["compiled"]
        stats["seconds"] = time.perf_counter() - t0
        return stats

    def save(self) -> bool:
        """Write newly-compiled programs back (atomic; merges with the
        current on-disk file first — entries are content-keyed, so
        concurrent replicas each persisting their own compiles converge
        on the union instead of last-writer dropping the other's work)."""
        with self._lock:
            if not (self.dir and self._dirty):
                return False
            merged = self._load_file()
            merged.update(self._payloads)
            self._payloads = merged
            blob = pickle.dumps({"format": _FORMAT,
                                 "programs": merged})
            self._dirty = False
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".warm.tmp")
        with os.fdopen(fd, "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._path())
        return True
