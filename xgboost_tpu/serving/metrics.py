"""Serving observability: per-model counters, queue depth, batch-size
histogram, and latency quantiles — rebased onto the telemetry registry.

Role model: the reference exposes none of this (its C API returns raw
buffers and leaves observability to the host process); a serving engine
needs its SLO signals built in.  Everything here is lock-cheap — counters
under a mutex, latencies in a fixed ring buffer — so the hot path pays
O(1) per request.  ``snapshot()`` renders the current state as a plain
dict (the shape ``scripts/bench_serve.py`` persists into BENCH_SERVE.json
— bitwise-stable across the telemetry rebase) and ``utils/observer.py``
can stream it for diff-friendly debugging.

Registry rebase (telemetry/registry.py): every mutation also feeds the
process-default registry — ``xtb_serve_requests_total{model=}``,
``xtb_serve_rows_total``, ``xtb_serve_errors_total``,
``xtb_serve_batches_total``, ``xtb_serve_batch_rows`` (histogram),
``xtb_serve_latency_seconds`` (histogram), ``xtb_serve_exec_seconds_total``,
``xtb_serve_queue_rows`` / ``xtb_serve_queue_peak`` (gauges), and
``xtb_compiles_steady{scope="serve"}`` — so ``telemetry.render_prometheus()``
exposes serving alongside training with no extra wiring.  The local ints
remain the source of truth for ``snapshot()``: registry series are
process-cumulative (every engine in the process adds to them, Prometheus
counter semantics), while each ServingMetrics instance reports its own
engine exactly as before.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from ..ops.predict import round_up_pow2
from ..telemetry.registry import get_registry
from ..utils import observer

_RING = 2048  # latency samples kept per model (reservoir of the recent past)

# pow2 row buckets 1..4096 then +Inf: the admission policy's natural shape
_BATCH_BUCKETS = tuple(float(1 << i) for i in range(13))
# request latencies: 10us .. ~40s exponential
_LATENCY_BUCKETS = tuple(1e-5 * (4.0 ** i) for i in range(12))


class _Instruments:
    """Registry families for the serving subsystem (created once per
    process, shared by every engine)."""

    _singleton = None

    def __init__(self) -> None:
        reg = get_registry()
        self.requests = reg.counter(
            "xtb_serve_requests_total", "predict requests", ("model",))
        self.rows = reg.counter(
            "xtb_serve_rows_total", "rows predicted", ("model",))
        self.errors = reg.counter(
            "xtb_serve_errors_total", "failed predict requests", ("model",))
        self.batches = reg.counter(
            "xtb_serve_batches_total", "coalesced batches executed",
            ("model",))
        self.shed = reg.counter(
            "xtb_serve_shed_total",
            "requests shed at admission (queue full)", ("model",))
        self.deadline = reg.counter(
            "xtb_serve_deadline_total",
            "requests abandoned at their deadline", ("model",))
        self.exec_seconds = reg.counter(
            "xtb_serve_exec_seconds_total",
            "device-execute seconds (batch granularity)", ("model",))
        self.batch_rows = reg.histogram(
            "xtb_serve_batch_rows", "rows per coalesced batch", ("model",),
            buckets=_BATCH_BUCKETS)
        self.latency = reg.histogram(
            "xtb_serve_latency_seconds", "request latency", ("model",),
            buckets=_LATENCY_BUCKETS)
        self.queue_rows = reg.gauge(
            "xtb_serve_queue_rows", "rows waiting in the micro-batcher")
        self.queue_peak = reg.gauge(
            "xtb_serve_queue_peak", "high-water mark of queued rows")
        self.compiles_warmup = reg.counter(
            "xtb_compiles_warmup",
            "programs compiled during engine warm-up", ("scope",)
        ).labels("serve")
        self.compiles_steady = reg.counter(
            "xtb_compiles_steady",
            "backend compiles after warm-up (SLO: 0)", ("scope",)
        ).labels("serve")

    @classmethod
    def get(cls) -> "_Instruments":
        if cls._singleton is None:
            cls._singleton = cls()
        return cls._singleton


class _ModelStats:
    __slots__ = ("requests", "rows", "errors", "batches", "batch_hist",
                 "lat_ns", "lat_idx", "lat_n", "exec_ns", "batched_rows",
                 "shed", "deadline",
                 "reg_requests", "reg_rows", "reg_errors", "reg_batches",
                 "reg_exec_seconds", "reg_batch_rows", "reg_latency",
                 "reg_shed", "reg_deadline")

    def __init__(self, name: str, instruments: _Instruments) -> None:
        self.requests = 0
        self.rows = 0
        self.errors = 0
        self.batches = 0
        self.shed = 0
        self.deadline = 0
        self.batch_hist: Dict[int, int] = {}  # pow2 batch-rows bucket -> count
        self.lat_ns = np.zeros(_RING, np.int64)  # request latency ring
        self.lat_idx = 0
        self.lat_n = 0
        self.exec_ns = 0  # total device-execute time (batch granularity)
        self.batched_rows = 0  # rows covered by exec_ns (direct rows are not)
        ins = instruments  # this model's registry children, resolved once
        self.reg_requests = ins.requests.labels(name)
        self.reg_rows = ins.rows.labels(name)
        self.reg_errors = ins.errors.labels(name)
        self.reg_batches = ins.batches.labels(name)
        self.reg_exec_seconds = ins.exec_seconds.labels(name)
        self.reg_batch_rows = ins.batch_rows.labels(name)
        self.reg_latency = ins.latency.labels(name)
        self.reg_shed = ins.shed.labels(name)
        self.reg_deadline = ins.deadline.labels(name)

    def add_latency(self, ns: int) -> None:
        self.lat_ns[self.lat_idx] = ns
        self.lat_idx = (self.lat_idx + 1) % _RING
        self.lat_n = min(self.lat_n + 1, _RING)
        self.reg_latency.observe(ns / 1e9)

    def quantiles_ms(self):
        if self.lat_n == 0:
            return {"p50": None, "p95": None, "p99": None}
        lat = self.lat_ns[: self.lat_n] / 1e6
        p50, p95, p99 = np.percentile(lat, [50, 95, 99])
        return {"p50": float(p50), "p95": float(p95), "p99": float(p99)}


class ServingMetrics:
    """Thread-safe metrics registry shared by engine + batcher."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._models: Dict[str, _ModelStats] = {}
        self._queue_rows = 0  # rows waiting in the micro-batcher (gauge)
        self._queue_peak = 0
        self._compiles_warmup = 0  # programs compiled during warm-up
        self._compiles_steady = 0  # programs compiled after warm-up (SLO: 0)
        self._ins = _Instruments.get()

    # compiles_* kept assignable/incrementable attributes for API compat
    # (engine.warmup does `metrics.compiles_warmup += n`); positive deltas
    # flow into the process-wide registry counters
    @property
    def compiles_warmup(self) -> int:
        return self._compiles_warmup

    @compiles_warmup.setter
    def compiles_warmup(self, v: int) -> None:
        with self._lock:
            d = int(v) - self._compiles_warmup
            self._compiles_warmup = int(v)
        if d > 0:
            self._ins.compiles_warmup.inc(d)

    @property
    def compiles_steady(self) -> int:
        return self._compiles_steady

    @compiles_steady.setter
    def compiles_steady(self, v: int) -> None:
        with self._lock:
            d = int(v) - self._compiles_steady
            self._compiles_steady = int(v)
        if d > 0:
            self._ins.compiles_steady.inc(d)

    def _stats(self, model: str) -> _ModelStats:
        s = self._models.get(model)
        if s is None:
            s = self._models.setdefault(model,
                                        _ModelStats(model, self._ins))
        return s

    # ------------------------------------------------------------- hot path
    def observe_request(self, model: str, rows: int, latency_ns: int) -> None:
        with self._lock:
            s = self._stats(model)
            s.requests += 1
            s.rows += int(rows)
            s.add_latency(int(latency_ns))
        s.reg_requests.inc()
        s.reg_rows.inc(int(rows))

    def observe_batch(self, model: str, rows: int, n_requests: int,
                      exec_ns: int) -> None:
        with self._lock:
            s = self._stats(model)
            s.batches += 1
            s.exec_ns += int(exec_ns)
            s.batched_rows += int(rows)
            b = round_up_pow2(rows)
            s.batch_hist[b] = s.batch_hist.get(b, 0) + 1
        s.reg_batches.inc()
        s.reg_exec_seconds.inc(exec_ns / 1e9)
        s.reg_batch_rows.observe(float(rows))

    def observe_error(self, model: str) -> None:
        with self._lock:
            s = self._stats(model)
            s.errors += 1
        s.reg_errors.inc()

    def observe_shed(self, model: str) -> None:
        """A request rejected at admission (bounded-queue load shedding)."""
        with self._lock:
            s = self._stats(model)
            s.shed += 1
        s.reg_shed.inc()

    def observe_deadline(self, model: str) -> None:
        """A caller gave up at its deadline (slow or dead worker)."""
        with self._lock:
            s = self._stats(model)
            s.deadline += 1
        s.reg_deadline.inc()

    def queue_delta(self, d_rows: int) -> None:
        with self._lock:
            prev = self._queue_rows
            self._queue_rows = max(0, prev + int(d_rows))
            self._queue_peak = max(self._queue_peak, self._queue_rows)
            # the process gauge accumulates DELTAS so several engines sum
            # instead of overwriting each other (each engine's contribution
            # is its clamped local depth, so the sum stays >= 0 and exact);
            # published under the lock so a preempted stale writer cannot
            # interleave.  The peak is raised via the atomic set_max — a
            # read-then-set pair here could regress it across engines.
            self._ins.queue_rows.inc(self._queue_rows - prev)
            self._ins.queue_peak.set_max(self._ins.queue_rows.get())

    def note_steady_compiles(self, n: int) -> None:
        """Record programs compiled OUTSIDE warm-up — the no-retrace SLO
        counter (a warm engine must keep this at zero).  Attribution is
        best-effort under concurrent COLD paths: each caller's before/after
        gauge window can include another thread's compiles (over-count), and
        a steady compile landing during someone else's warmup() is credited
        to warm-up instead.  A warm engine serializes batches through one
        worker and compiles nothing, so the zero-is-zero reading — the one
        the SLO and the tests rely on — is exact."""
        with self._lock:
            self._compiles_steady += int(n)
        if n > 0:
            self._ins.compiles_steady.inc(int(n))

    # ------------------------------------------------------------- read side
    def queue_depth(self) -> int:
        with self._lock:
            return self._queue_rows

    def snapshot(self) -> dict:
        with self._lock:
            models = {}
            for name, s in self._models.items():
                q = s.quantiles_ms()
                total_s = s.exec_ns / 1e9
                models[name] = {
                    "requests": s.requests,
                    "rows": s.rows,
                    "errors": s.errors,
                    "shed": s.shed,
                    "deadline": s.deadline,
                    "batches": s.batches,
                    "batch_size_hist": {str(k): v for k, v in
                                        sorted(s.batch_hist.items())},
                    "latency_ms": q,
                    # throughput over BATCHED traffic only: exec_ns is
                    # accumulated per coalesced batch, so direct (un-timed)
                    # predict rows must not inflate the numerator
                    "rows_per_s": (s.batched_rows / total_s)
                    if total_s > 0 else None,
                }
            return {
                "queue_depth": self._queue_rows,
                "queue_peak": self._queue_peak,
                "compiles_warmup": self._compiles_warmup,
                "compiles_steady": self._compiles_steady,
                "models": models,
            }

    def export(self, tag: str = "serving") -> dict:
        """Snapshot + stream through the TrainingObserver channel when the
        debug observer is enabled (utils/observer.py)."""
        snap = self.snapshot()
        observer.observe_serving(snap, tag=tag)
        return snap

    def reset_latencies(self, model: Optional[str] = None) -> None:
        with self._lock:
            targets = ([self._models[model]] if model in self._models
                       else list(self._models.values()) if model is None
                       else [])
            for s in targets:
                s.lat_idx = s.lat_n = 0
