"""Serving observability: per-model counters, queue depth, batch-size
histogram, and latency quantiles.

Role model: the reference exposes none of this (its C API returns raw
buffers and leaves observability to the host process); a serving engine
needs its SLO signals built in.  Everything here is lock-cheap — counters
under a mutex, latencies in a fixed ring buffer — so the hot path pays
O(1) per request.  ``snapshot()`` renders the current state as a plain
dict (the shape ``scripts/bench_serve.py`` persists into BENCH_SERVE.json)
and ``utils/observer.py`` can stream it for diff-friendly debugging.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from ..ops.predict import round_up_pow2
from ..utils import observer

_RING = 2048  # latency samples kept per model (reservoir of the recent past)


class _ModelStats:
    __slots__ = ("requests", "rows", "errors", "batches", "batch_hist",
                 "lat_ns", "lat_idx", "lat_n", "exec_ns", "batched_rows")

    def __init__(self) -> None:
        self.requests = 0
        self.rows = 0
        self.errors = 0
        self.batches = 0
        self.batch_hist: Dict[int, int] = {}  # pow2 batch-rows bucket -> count
        self.lat_ns = np.zeros(_RING, np.int64)  # request latency ring
        self.lat_idx = 0
        self.lat_n = 0
        self.exec_ns = 0  # total device-execute time (batch granularity)
        self.batched_rows = 0  # rows covered by exec_ns (direct rows are not)

    def add_latency(self, ns: int) -> None:
        self.lat_ns[self.lat_idx] = ns
        self.lat_idx = (self.lat_idx + 1) % _RING
        self.lat_n = min(self.lat_n + 1, _RING)

    def quantiles_ms(self):
        if self.lat_n == 0:
            return {"p50": None, "p95": None, "p99": None}
        lat = self.lat_ns[: self.lat_n] / 1e6
        p50, p95, p99 = np.percentile(lat, [50, 95, 99])
        return {"p50": float(p50), "p95": float(p95), "p99": float(p99)}


class ServingMetrics:
    """Thread-safe metrics registry shared by engine + batcher."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._models: Dict[str, _ModelStats] = {}
        self._queue_rows = 0  # rows waiting in the micro-batcher (gauge)
        self._queue_peak = 0
        self.compiles_warmup = 0  # programs compiled during warm-up
        self.compiles_steady = 0  # programs compiled after warm-up (SLO: 0)

    def _stats(self, model: str) -> _ModelStats:
        s = self._models.get(model)
        if s is None:
            s = self._models.setdefault(model, _ModelStats())
        return s

    # ------------------------------------------------------------- hot path
    def observe_request(self, model: str, rows: int, latency_ns: int) -> None:
        with self._lock:
            s = self._stats(model)
            s.requests += 1
            s.rows += int(rows)
            s.add_latency(int(latency_ns))

    def observe_batch(self, model: str, rows: int, n_requests: int,
                      exec_ns: int) -> None:
        with self._lock:
            s = self._stats(model)
            s.batches += 1
            s.exec_ns += int(exec_ns)
            s.batched_rows += int(rows)
            b = round_up_pow2(rows)
            s.batch_hist[b] = s.batch_hist.get(b, 0) + 1

    def observe_error(self, model: str) -> None:
        with self._lock:
            self._stats(model).errors += 1

    def queue_delta(self, d_rows: int) -> None:
        with self._lock:
            self._queue_rows = max(0, self._queue_rows + int(d_rows))
            self._queue_peak = max(self._queue_peak, self._queue_rows)

    def note_steady_compiles(self, n: int) -> None:
        """Record programs compiled OUTSIDE warm-up — the no-retrace SLO
        counter (a warm engine must keep this at zero).  Attribution is
        best-effort under concurrent COLD paths: each caller's before/after
        gauge window can include another thread's compiles (over-count), and
        a steady compile landing during someone else's warmup() is credited
        to warm-up instead.  A warm engine serializes batches through one
        worker and compiles nothing, so the zero-is-zero reading — the one
        the SLO and the tests rely on — is exact."""
        with self._lock:
            self.compiles_steady += int(n)

    # ------------------------------------------------------------- read side
    def queue_depth(self) -> int:
        with self._lock:
            return self._queue_rows

    def snapshot(self) -> dict:
        with self._lock:
            models = {}
            for name, s in self._models.items():
                q = s.quantiles_ms()
                total_s = s.exec_ns / 1e9
                models[name] = {
                    "requests": s.requests,
                    "rows": s.rows,
                    "errors": s.errors,
                    "batches": s.batches,
                    "batch_size_hist": {str(k): v for k, v in
                                        sorted(s.batch_hist.items())},
                    "latency_ms": q,
                    # throughput over BATCHED traffic only: exec_ns is
                    # accumulated per coalesced batch, so direct (un-timed)
                    # predict rows must not inflate the numerator
                    "rows_per_s": (s.batched_rows / total_s)
                    if total_s > 0 else None,
                }
            return {
                "queue_depth": self._queue_rows,
                "queue_peak": self._queue_peak,
                "compiles_warmup": self.compiles_warmup,
                "compiles_steady": self.compiles_steady,
                "models": models,
            }

    def export(self, tag: str = "serving") -> dict:
        """Snapshot + stream through the TrainingObserver channel when the
        debug observer is enabled (utils/observer.py)."""
        snap = self.snapshot()
        observer.observe_serving(snap, tag=tag)
        return snap

    def reset_latencies(self, model: Optional[str] = None) -> None:
        with self._lock:
            targets = ([self._models[model]] if model in self._models
                       else list(self._models.values()) if model is None
                       else [])
            for s in targets:
                s.lat_idx = s.lat_n = 0
