"""xgboost_tpu.serving — batched, multi-model inference engine.

The production serving layer over the predictor (docs/serving.md):

- :class:`ServingEngine` — pre-compiled padded-bucket predict programs,
  dynamic micro-batching, per-model metrics with latency quantiles.
- :class:`ServeConfig` — SLO knobs (max_batch, max_delay_us, residency cap,
  warm-up buckets).
- :class:`ModelRegistry` — versioned LRU model residency with pinning.
- :class:`InferenceSnapshot` — immutable device-resident view of a trained
  Booster (``Booster.inference_snapshot()``).
- :class:`MicroBatcher` / :class:`ServingMetrics` — the coalescing and
  observability building blocks, usable standalone.

Quick start::

    import xgboost_tpu as xtb
    from xgboost_tpu.serving import ServingEngine

    eng = ServingEngine(max_delay_us=1000)
    eng.add_model("ctr", booster)           # or a .json/.ubj path
    probs = eng.predict("ctr", rows)        # N threads may call this
    print(eng.metrics_snapshot()["models"]["ctr"]["latency_ms"])
"""
from .batcher import MicroBatcher, QueueFullError, WorkerDiedError
from .engine import ServeConfig, ServingEngine
from .fleet import FleetConfig, ServingFleet, SLOClass
from .metrics import ServingMetrics
from .modelstore import ModelStore
from .registry import ModelRegistry
from .snapshot import InferenceSnapshot
from .warmcache import WarmProgramCache, configure_persistent_cache

__all__ = [
    "ServingEngine",
    "ServeConfig",
    "ServingFleet",
    "FleetConfig",
    "SLOClass",
    "ModelStore",
    "WarmProgramCache",
    "configure_persistent_cache",
    "ModelRegistry",
    "InferenceSnapshot",
    "MicroBatcher",
    "ServingMetrics",
    "WorkerDiedError",
    "QueueFullError",
]
