"""Serving fleet: N replica processes behind one (or N sharded) dispatchers.

The multi-process scale-out layer over :class:`ServingEngine`
(docs/serving.md "Fleet" has the full topology/tuning guide):

**Sharding** (``n_shards`` > 1, docs/serving.md "Sharded topology"):
past ~4 replicas a single dispatcher thread + one big cv lock becomes
the ceiling, so the fleet splits into shared-nothing shards.  Each shard
is a full single-shard fleet — its own listener socket, DispatchQueue,
cv lock, rx threads, heartbeat/breaker/hedge state, and replica group
(labels prefixed ``s{k}:``) — while the mmap ModelStore, the warm
compile cache, the AIMD/brownout governor, and the telemetry registry
stay shared (per-shard series carry a ``shard=`` label).  The front-end
object routes ``submit`` by a stable hash of (tenant, model)
(:func:`shard_of`) and fans admin/lifecycle calls out to every shard;
every reliability semantic below holds *per shard* (a killed replica's
window-1 batch requeues within its own shard's replica group).

- **Replicas** are launcher-spawned subprocesses (``serving/replica.py``)
  sharing the mmap :class:`ModelStore` (one host copy of every booster)
  and the warm compile cache (``warmcache.py`` — AOT program file + XLA
  persistent cache), so adding a replica costs milliseconds of warm work,
  not seconds of compiles.
- **The dispatcher** (this module) owns admission and routing: requests
  queue centrally in priority order (per-tenant :class:`SLOClass`), and
  each replica holds AT MOST ONE batch in flight.  Central queueing +
  window-1 is a deliberate failure-semantics choice: when a replica dies,
  everything except its single in-flight batch is still in the
  dispatcher's queue — and the in-flight batch itself is requeued onto a
  live replica (predict is idempotent), so replica death drops nothing
  (``xtb_fleet_rerouted_total`` counts the reroutes; the fleet smoke and
  ``tests/test_fleet.py`` pin the no-loss contract).
- **The request path is zero-copy** end to end (``wire.py``): the
  dispatcher routes on the tiny JSON header and forwards Arrow IPC /
  raw-f32 payload buffers verbatim — row bytes are never deserialized,
  copied, or even looked at outside the replica.
- **Failure handling** rides the launcher's machinery: replica stderr is
  captured per process, deaths are tolerated and respawned up to
  ``max_respawns``, and a fleet that loses every replica (or can't start
  one) raises :class:`~xgboost_tpu.launcher.WorkerFailedError` carrying
  each corpse's exit code + stderr tail.

Degradation is explicit, per tenant class: beyond ``max_queue`` queued
requests the LOWEST-priority newest request is shed
(:class:`~xgboost_tpu.serving.batcher.QueueFullError`,
``xtb_fleet_shed_total{slo=}``); a request older than its class deadline
is expired in-queue (``TimeoutError``, ``xtb_fleet_deadline_total{slo=}``)
instead of wasting replica time on an answer nobody is waiting for.

The ``fleet.dispatch`` fault seam fires right before a request is handed
to a replica: ``exception`` fails that request, ``delay`` stalls the
dispatcher, ``drop_connection`` severs the chosen replica's socket — the
deterministic stand-in for a replica vanishing mid-conversation
(docs/reliability.md).

**Degraded-network survival** (docs/reliability.md "Degraded
networks"): a replica that is merely *slow* or *half-open* (process
alive, one direction blackholed) never EOFs, so the death path above
cannot see it.  Three layers close that gap without bigger timeouts:

- **Heartbeats**: the dispatcher pings every replica on a schedule over
  the same serialized control-frame path (``wire.PING``/``wire.PONG``);
  a replica with no pong AND no other frame for ``heartbeat_timeout_s``
  is declared dead — which also folds first-response liveness in (a
  replica that acks ``ready`` and then never answers its first predict
  trips the same deadline instead of coasting to the global one).
- **Circuit breaker**: a per-replica EWMA of send->result latency
  trips closed -> open when it exceeds ``breaker_latency_s``, ejecting
  the slow replica from dispatch *before* it blows the SLO; after
  ``breaker_cooldown_s`` a single half-open probe request readmits it
  on success (closed) or re-opens on failure.
- **Hedged dispatch**: an in-flight predict older than the
  ``hedge_quantile`` of recent latencies (floored at ``hedge_min_s``)
  is re-issued to a free replica as a twin with a fresh id sharing the
  SAME future — replicas are deterministic, so the first result to
  settle wins bitwise-identically and the loser is discarded by the id
  check (``xtb_net_hedge_*`` counts issued/won/wasted).  Hedging is
  bitwise-neutral by construction: hedge-on returns exactly the bytes
  hedge-off would.

**Lifecycle integration** (docs/serving.md "Online model lifecycle"):
:meth:`ServingFleet.load_version` / :meth:`~ServingFleet.activate_version`
/ :meth:`~ServingFleet.retire_version` broadcast control frames that ride
each replica's serialized connection — a replica processes them strictly
after every predict dispatched before them, which is exactly the
"retire only after in-flight batches drain" contract.  ``activate_version``
durably commits the store manifest FIRST, so a replica that dies and
respawns mid-broadcast reads the committed version at startup and
converges with the survivors.  **Shadow scoring**
(:meth:`~ServingFleet.set_shadow`) duplicates a deterministic 1-in-N
subset of a model's unversioned traffic onto a candidate version; the
comparator feeds ``xtb_lifecycle_shadow_*`` divergence series and the
per-version ``xtb_fleet_version_latency_seconds`` histogram without the
duplicated result ever reaching a caller.
"""
from __future__ import annotations

import dataclasses
import errno
import heapq
import itertools
import json
import os
import sys
import tempfile
import threading
import time
import warnings
import zlib
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeout
from socket import socket as Socket
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..launcher import WorkerFailedError, spawn_worker, stderr_tail
from ..reliability import faults as _faults
from ..reliability import lockdep as _lockdep
from ..reliability import resources as _resources
from ..telemetry import distributed as _distributed
from ..telemetry import flight as _flight
from ..telemetry import profiler as _profiler
from ..telemetry import trace as _trace
from ..telemetry.registry import get_registry
from . import wire
from .batcher import QueueFullError

_LATENCY_BUCKETS = tuple(1e-5 * (4.0 ** i) for i in range(12))
_COLDSTART_BUCKETS = tuple(0.01 * (2.0 ** i) for i in range(14))
# prediction divergence spans "bitwise identical continuation" (0) through
# "differently-shaped model" (O(1)); decades, not latency quartics
_SHADOW_BUCKETS = tuple(1e-9 * (10.0 ** i) for i in range(10))
# a two-sample KS statistic lives in [0, 1]: a handful of decision points
# from "indistinguishable distributions" to "disjoint supports"
_KS_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.2, 0.5)
# PSI's conventional decision points straddle 0.1 ("noticeable shift") and
# 0.25 ("act"); decades around them, open-ended above
_PSI_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)

# cumulative per-frame read budget on the dispatcher's rx loops (the
# slow-loris bound in wire.recv_frame): a peer trickling one byte per
# idle interval gets this much wall per frame TOTAL, not per read.
# Generous by default — a full 2 GiB payload over loopback clears it by
# orders of magnitude — and env-tunable for tight test harnesses.
FRAME_BUDGET_ENV = "XGBOOST_TPU_FRAME_BUDGET_S"


def _frame_budget_s() -> Optional[float]:
    raw = os.environ.get(FRAME_BUDGET_ENV, "").strip()
    if not raw:
        return 120.0
    try:
        v = float(raw)
    except ValueError:
        return 120.0
    return v if v > 0 else None


# default dispatcher shard count when FleetConfig.n_shards is 0 ("auto"):
# one shard preserves the classic single-dispatcher topology exactly
SHARDS_ENV = "XGBOOST_TPU_FLEET_SHARDS"
# SO_REUSEPORT accept path for sharded fleets: every shard binds the SAME
# port and an accepted replica connection is handed to its owning shard by
# hello-label prefix.  Default off — per-shard listener ports need no
# kernel support and no cross-shard handoff.
REUSEPORT_ENV = "XGBOOST_TPU_FLEET_REUSEPORT"


def shard_of(model: str, tenant: Optional[str], n_shards: int) -> int:
    """Client-side partition for the sharded front-end: which dispatcher
    shard owns (tenant, model) traffic.  A pure hash of the routing key —
    no registry, no state — so the SAME tenant/model pair lands on the
    SAME shard across respawns, restarts, and processes (the routing
    contract docs/serving.md pins and tests/test_fleet_shards.py
    enforces)."""
    key = f"{tenant or ''}\x00{model}".encode()
    return zlib.crc32(key) % max(1, int(n_shards))


def _ks_stat(a: np.ndarray, b: np.ndarray) -> float:
    """Two-sample Kolmogorov–Smirnov statistic between flattened
    prediction sets: max |ECDF_a - ECDF_b|.  Complements the mean-abs
    divergence — a candidate can match the incumbent on average while
    redistributing scores across the ranking (the failure mode that
    matters for AUC-shaped objectives), and KS catches exactly that."""
    a = np.sort(np.asarray(a, np.float64).ravel())
    b = np.sort(np.asarray(b, np.float64).ravel())
    if a.size == 0 or b.size == 0:
        return 0.0
    grid = np.concatenate([a, b])
    cdf_a = np.searchsorted(a, grid, side="right") / a.size
    cdf_b = np.searchsorted(b, grid, side="right") / b.size
    return float(np.max(np.abs(cdf_a - cdf_b)))


def _psi(a: np.ndarray, b: np.ndarray, bins: int = 10) -> float:
    """Population stability index of ``b`` against reference ``a``, over
    ``a``'s decile bins: sum over bins of (p_a - p_b) * ln(p_a / p_b).
    The third comparator lens next to mean-divergence and KS — KS reports
    the single worst ECDF gap, PSI integrates shift across the whole
    distribution, so a broad small drift that never opens one large gap
    still registers.  Bin fractions are clamped to 1e-6 (empty-bin PSI is
    finite, and a bin emptying out IS the signal)."""
    a = np.asarray(a, np.float64).ravel()
    b = np.asarray(b, np.float64).ravel()
    if a.size == 0 or b.size == 0:
        return 0.0
    edges = np.quantile(a, np.linspace(0.0, 1.0, bins + 1)[1:-1])
    pa = np.bincount(np.searchsorted(edges, a, side="right"),
                     minlength=bins)[:bins] / a.size
    pb = np.bincount(np.searchsorted(edges, b, side="right"),
                     minlength=bins)[:bins] / b.size
    pa = np.clip(pa, 1e-6, None)
    pb = np.clip(pb, 1e-6, None)
    return float(np.sum((pa - pb) * np.log(pa / pb)))


def _calibration_gap(a: np.ndarray, b: np.ndarray, bins: int = 10) -> float:
    """Max per-decile calibration gap: bucket the pair's rows by the
    INCUMBENT's score deciles, compare each bucket's expected rate (the
    incumbent's mean score — what the serving distribution promised) with
    the candidate's observed mean on the same rows.  A candidate can pass
    mean-divergence and KS while systematically re-scoring one decile
    (e.g. flattening the top bucket a bid system prices from); the
    per-decile max catches exactly that."""
    a = np.asarray(a, np.float64).ravel()
    b = np.asarray(b, np.float64).ravel()
    if a.size == 0 or a.size != b.size:
        return 0.0
    edges = np.quantile(a, np.linspace(0.0, 1.0, bins + 1)[1:-1])
    idx = np.searchsorted(edges, a, side="right")
    gap = 0.0
    for d in range(bins):
        m = idx == d
        if m.any():
            gap = max(gap, abs(float(a[m].mean()) - float(b[m].mean())))
    return gap


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One tenant class: who gets served first and how long they wait.

    ``priority``: higher dispatches first and sheds last.  ``deadline_s``:
    submit-to-result budget — expired queued requests fail fast with
    ``TimeoutError`` instead of occupying a replica (None = wait forever).
    """

    name: str = "default"
    priority: int = 0
    deadline_s: Optional[float] = None


# shadow twins are discardable measurements: they outrank NOTHING, so
# under queue pressure a twin sheds itself (a comparator "failure")
# rather than evicting any real caller's request
_SHADOW_SLO = SLOClass("shadow", priority=-(2 ** 31))


@dataclasses.dataclass
class FleetConfig:
    n_replicas: int = 2
    store_dir: Optional[str] = None   # None = private temp dir
    cache_dir: Optional[str] = None   # None = no warm cache (always cold)
    warmup_buckets: Tuple[int, ...] = ()  # () = replica default ladder
    max_queue: int = 4096             # queued requests before shedding
    slo_classes: Dict[str, SLOClass] = dataclasses.field(
        default_factory=dict)       # tenant -> class
    default_slo: SLOClass = dataclasses.field(default_factory=SLOClass)
    nthread_per_replica: int = 1      # native pool width per replica
    max_respawns: int = 2
    ready_timeout_s: float = 300.0
    platform: Optional[str] = None    # replica jax platform (None = inherit)
    # --- degraded-network survival (docs/reliability.md "Degraded
    # networks"); breaker and hedging default OFF, heartbeats default ON
    heartbeat_s: float = 2.0          # ping cadence (0 = no heartbeats)
    heartbeat_timeout_s: float = 30.0  # no pong AND no frame -> declared
    breaker_latency_s: float = 0.0    # EWMA trip point (0 = breaker off)
    breaker_cooldown_s: float = 2.0   # open -> half-open probe delay
    hedge_quantile: float = 0.0       # latency quantile (0 = no hedging)
    hedge_min_s: float = 0.01         # hedge budget floor
    # --- sharded front-end (docs/serving.md "Sharded topology"):
    # n_shards > 1 splits the fleet into shared-nothing dispatcher shards,
    # each owning n_replicas/n_shards replicas, its own listener, queue,
    # rx threads, and degraded-network state; submit() routes by
    # hash(tenant, model).  0 = XGBOOST_TPU_FLEET_SHARDS (default 1).
    n_shards: int = 0
    # None = XGBOOST_TPU_FLEET_REUSEPORT (default off): shards share one
    # SO_REUSEPORT listening port instead of per-shard ports
    reuseport: Optional[bool] = None

    def __post_init__(self) -> None:
        if self.n_shards == 0:
            raw = os.environ.get(SHARDS_ENV, "").strip()
            try:
                self.n_shards = int(raw) if raw else 1
            except ValueError:
                self.n_shards = 1
        if self.reuseport is None:
            self.reuseport = os.environ.get(
                REUSEPORT_ENV, "").strip().lower() not in (
                    "", "0", "false", "off", "no")
        if self.n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        if self.n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        if self.n_replicas % self.n_shards:
            # n_replicas is the fleet TOTAL; every shard owns an equal
            # replica group (uneven groups would skew both the routing
            # contract and the saturation math)
            raise ValueError(
                f"n_replicas ({self.n_replicas}) must be divisible by "
                f"n_shards ({self.n_shards})")
        if self.max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if not 0.0 <= self.hedge_quantile < 1.0:
            raise ValueError("hedge_quantile must be in [0, 1)")

    def resolve_slo(self, tenant: Optional[str]) -> SLOClass:
        if tenant is None:
            return self.default_slo
        return self.slo_classes.get(tenant, self.default_slo)


class _Instruments:
    """xtb_fleet_* registry families (process-wide singleton)."""

    _singleton = None

    def __init__(self) -> None:
        reg = get_registry()
        self.replicas = reg.gauge(
            "xtb_fleet_replicas", "live (ready) fleet replicas")
        self.requests = reg.counter(
            "xtb_fleet_requests_total", "requests dispatched to replicas",
            ("model",))
        self.rerouted = reg.counter(
            "xtb_fleet_rerouted_total",
            "in-flight requests requeued after a replica death")
        self.respawns = reg.counter(
            "xtb_fleet_respawns_total", "replacement replicas spawned")
        self.shed = reg.counter(
            "xtb_fleet_shed_total",
            "requests shed at admission (queue full)", ("slo",))
        self.deadline = reg.counter(
            "xtb_fleet_deadline_total",
            "requests expired before/at their class deadline", ("slo",))
        self.latency = reg.histogram(
            "xtb_fleet_latency_seconds", "submit-to-result request latency",
            ("model",), buckets=_LATENCY_BUCKETS)
        self.coldstart = reg.histogram(
            "xtb_fleet_coldstart_seconds",
            "replica warm-work seconds at ready, by compile-cache state",
            ("cache",), buckets=_COLDSTART_BUCKETS)
        self.version_latency = reg.histogram(
            "xtb_fleet_version_latency_seconds",
            "submit-to-result latency by served model version",
            ("model", "version"), buckets=_LATENCY_BUCKETS)
        self.shadow_requests = reg.counter(
            "xtb_lifecycle_shadow_requests_total",
            "shadow-scored request pairs compared", ("model",))
        self.shadow_failures = reg.counter(
            "xtb_lifecycle_shadow_failures_total",
            "shadow pairs that could not be compared (either side failed "
            "or was shed)", ("model",))
        self.shadow_divergence = reg.histogram(
            "xtb_lifecycle_shadow_divergence",
            "mean |candidate - incumbent| prediction divergence per "
            "shadow-scored request", ("model",), buckets=_SHADOW_BUCKETS)
        self.shadow_ks = reg.histogram(
            "xtb_lifecycle_shadow_ks",
            "two-sample KS statistic between candidate and incumbent "
            "prediction distributions per shadow-scored request",
            ("model",), buckets=_KS_BUCKETS)
        self.shadow_psi = reg.histogram(
            "xtb_lifecycle_shadow_psi",
            "population stability index of candidate vs incumbent "
            "prediction distributions per shadow-scored request",
            ("model",), buckets=_PSI_BUCKETS)
        self.shadow_calibration = reg.histogram(
            "xtb_lifecycle_shadow_calibration",
            "max per-incumbent-decile calibration gap (expected vs "
            "observed mean score) per shadow-scored request",
            ("model",), buckets=_SHADOW_BUCKETS)
        self.feedback_frames = reg.counter(
            "xtb_online_feedback_frames_total",
            "feedback-capture frames received from replicas", ("model",))
        self.feedback_rows = reg.counter(
            "xtb_online_sampled_rows_total",
            "feature rows received through feedback capture", ("model",))
        self.brownout = reg.counter(
            "xtb_fleet_brownout_total",
            "requests shed at admission by the resource-pressure "
            "brownout (low-SLO tenants first)", ("slo",))
        self.admission_window = reg.gauge(
            "xtb_fleet_admission_window",
            "current AIMD admission window (queued requests admitted "
            "before shedding; collapses under overload, recovers on "
            "completions)")
        self.hb_rtt = reg.histogram(
            "xtb_net_heartbeat_rtt_seconds",
            "application-level ping->pong round trip per replica",
            ("replica",), buckets=_LATENCY_BUCKETS)
        self.breaker_state = reg.gauge(
            "xtb_net_breaker_state",
            "per-replica circuit breaker state (0 closed, 1 open, "
            "2 half-open)", ("replica",))
        self.breaker_transitions = reg.counter(
            "xtb_net_breaker_transitions_total",
            "circuit breaker state transitions, by target state", ("to",))
        self.hedges = reg.counter(
            "xtb_net_hedges_total",
            "hedge twins issued for in-flight requests past the hedge "
            "budget")
        self.hedge_wins = reg.counter(
            "xtb_net_hedge_wins_total",
            "hedged requests whose twin's result settled the caller "
            "first")
        self.hedge_wasted = reg.counter(
            "xtb_net_hedge_wasted_total",
            "duplicate hedge-pair results discarded after the pair's "
            "first settle")
        self.label_frames = reg.counter(
            "xtb_net_label_frames_total",
            "op=\"label\" frames received over label-feed connections")
        # --- sharded front-end series (docs/serving.md "Sharded
        # topology"): per-shard throughput + rx-loop occupancy, labeled by
        # owning dispatcher shard ("0" on an unsharded fleet)
        self.shards = reg.gauge(
            "xtb_fleet_shards", "configured dispatcher shards")
        self.shard_requests = reg.counter(
            "xtb_fleet_shard_requests_total",
            "predict requests dispatched, by owning dispatcher shard",
            ("shard",))
        self.shard_rows = reg.counter(
            "xtb_fleet_shard_rows_total",
            "payload rows dispatched, by owning dispatcher shard",
            ("shard",))
        self.shard_rx_busy = reg.counter(
            "xtb_fleet_shard_rx_busy_seconds_total",
            "rx-loop seconds spent processing received frames (vs "
            "blocked waiting for one), by dispatcher shard — busy/wall "
            "is the shard's rx occupancy fraction", ("shard",))

    @classmethod
    def get(cls) -> "_Instruments":
        if cls._singleton is None:
            cls._singleton = cls()
        return cls._singleton


class AdaptiveAdmission:
    """AIMD admission control over the dispatch queue (pure state machine;
    the fleet wires its transitions to the resource governor, tests drive
    it directly).

    The fixed ``max_queue`` bound is the right *ceiling*, but under
    overload it is the wrong *operating point*: a queue allowed to sit at
    the ceiling serves every request at worst-case latency before finally
    shedding.  TCP's answer applies directly — multiplicative decrease on
    every pressure event (a shed, an in-queue deadline expiry, a replica
    death), additive increase (+1) per completed request, clamped to
    ``[floor, max_queue]``.  A saturated fleet converges to a small
    admission window (shedding early, keeping queue wait bounded); a
    recovered fleet climbs back to the ceiling in ~max_queue completions.

    ``on_pressure()`` returns True on the transition onto the floor —
    the fleet's cue to declare overload to the resource governor (which
    starts the SLO brownout); ``on_ok()`` returns True on the recovery
    transition (window back above half the ceiling) — the cue to restore
    it.  Both edges fire once per excursion, so governor levels move on
    state *transitions*, never per request.
    """

    def __init__(self, max_queue: int, floor: Optional[int] = None) -> None:
        self.max_queue = max(int(max_queue), 1)
        self.floor = max(1, min(int(floor) if floor is not None else 8,
                                self.max_queue))
        # governor coupling needs room between the edges: the floor edge
        # (declare overload) and the recovery edge (ceiling/2) must be at
        # least a doubling apart, or a single completion right after a
        # shed would flap the overload level per request.  Queues under
        # 4x the floor (tests, toy configs) keep the AIMD window but
        # never couple to the governor.
        self.coupled = self.max_queue >= 4 * self.floor
        self._window = float(self.max_queue)
        self._lock = threading.Lock()
        self._floored = False

    def limit(self) -> int:
        return int(self._window)

    def on_pressure(self) -> bool:
        """Multiplicative decrease; True on the onto-the-floor edge
        (coupled queues only — see ``__init__``)."""
        with self._lock:
            self._window = max(float(self.floor), self._window / 2.0)
            hit = self._window <= self.floor and self.coupled
            edge = hit and not self._floored
            if hit:
                self._floored = True
        return edge

    def on_ok(self) -> bool:
        """Additive increase; True on the recovered edge (window back
        above half the ceiling — >= 2x the floor on any coupled queue —
        after having been floored)."""
        with self._lock:
            self._window = min(float(self.max_queue), self._window + 1.0)
            recovered = (self._floored
                         and self._window >= self.max_queue / 2.0)
            if recovered:
                self._floored = False
        return recovered


class _Request:
    __slots__ = ("id", "model", "header", "payload", "future",
                 "slo", "deadline", "t_submit", "tries", "state",
                 "t_submit_ns", "t_send_ns", "hedge", "hedged")

    def __init__(self, rid: int, model: str, header: dict, payload,
                 slo: SLOClass) -> None:
        self.id = rid
        self.model = model
        self.header = header
        self.payload = payload
        self.future: Future = Future()
        self.slo = slo
        self.t_submit = time.monotonic()
        self.deadline = (self.t_submit + slo.deadline_s
                         if slo.deadline_s is not None else None)
        self.tries = 0
        self.state = "queued"  # queued | inflight | done | shed | expired
        # trace bracket anchors (perf_counter_ns: on Linux a system-wide
        # monotonic epoch, so dispatcher and replica events align in one
        # merged chrome://tracing timeline)
        self.t_submit_ns = time.perf_counter_ns()
        self.t_send_ns = 0
        # hedged dispatch: `hedge` marks a twin (fresh id, SHARED future);
        # `hedged` marks an original that already has a twin out, so the
        # tick never double-hedges
        self.hedge = False
        self.hedged = False


class DispatchQueue:
    """Priority queue with SLO-ordered shedding (NOT thread-safe: the
    fleet holds its lock around every call; standalone so the shed/expiry
    policy is unit-testable without processes).

    Order: higher ``SLOClass.priority`` first, FIFO within a class.  When
    full, the victim is the NEWEST request of the LOWEST priority class —
    and only if the incoming request outranks it; an incoming request that
    doesn't outrank anyone is shed itself (equal priority sheds the
    newcomer: FIFO fairness).
    """

    def __init__(self, max_queue: int) -> None:
        self.max_queue = int(max_queue)
        self._heap: List[Tuple[int, int, _Request]] = []
        self._seq = itertools.count()
        self._live = 0

    def __len__(self) -> int:
        return self._live

    def push(self, req: _Request,
             limit: Optional[int] = None) -> Optional[_Request]:
        """Admit ``req``; returns the request shed to make room (which may
        be ``req`` itself), or None when nothing was shed.  ``limit``
        (the AIMD admission window) tightens the bound below
        ``max_queue`` for this push — the ceiling still always applies."""
        victim = None
        cap = self.max_queue if limit is None else max(
            1, min(int(limit), self.max_queue))
        if self._live >= cap:
            # victim = newest request of the lowest-priority class (heap
            # entries carry (-priority, seq): max picks exactly that).
            # Removed PHYSICALLY, not just by state: under a sustained
            # overload with no pops (every replica stalled) lazy removal
            # would grow the heap — and the shed payload buffers it
            # retains — by one entry per shed, without bound.
            cands = [e for e in self._heap if e[2].state == "queued"]
            entry = max(cands, key=lambda e: (e[0], e[1]), default=None)
            if entry is not None and -entry[0] < req.priority_():
                victim = entry[2]
                victim.state = "shed"
                self._heap.remove(entry)
                heapq.heapify(self._heap)
                self._live -= 1
            else:  # nobody outranked: the newcomer is the victim
                req.state = "shed"
                return req
        heapq.heappush(self._heap, (-req.priority_(), next(self._seq), req))
        self._live += 1
        return victim

    def pop(self, now: float) -> Tuple[Optional[_Request], List[_Request]]:
        """Highest-priority oldest live request, plus any expired on the
        way (deadline passed while queued)."""
        expired: List[_Request] = []
        while self._heap:
            _, _, req = self._heap[0]
            if req.state != "queued":  # lazily drop shed/expired/cancelled
                heapq.heappop(self._heap)
                continue
            if req.future.cancelled() or req.future.done():
                # cancelled: the caller timed out — don't burn a replica on
                # an answer nobody will read.  done: a hedge twin already
                # settled the shared future while this side sat requeued
                # after its replica died — dispatching it again is pure
                # waste.
                heapq.heappop(self._heap)
                req.state = "done"
                self._live -= 1
                continue
            if req.deadline is not None and now >= req.deadline:
                heapq.heappop(self._heap)
                req.state = "expired"
                self._live -= 1
                expired.append(req)
                continue
            heapq.heappop(self._heap)
            req.state = "inflight"
            self._live -= 1
            return req, expired
        return None, expired

    def requeue_front(self, req: _Request) -> None:
        """Put a rerouted in-flight request back at the FRONT of its
        class (seq below everything queued so far)."""
        req.state = "queued"
        # negative seq sorts below every normally-pushed entry of the class
        heapq.heappush(self._heap, (-req.priority_(), -next(self._seq), req))
        self._live += 1

    def drain(self) -> List[_Request]:
        out = [e[2] for e in self._heap if e[2].state == "queued"]
        for r in out:
            r.state = "shed"
        self._heap.clear()
        self._live = 0
        return out


# priority accessor lives on the request so DispatchQueue never imports
# SLOClass details
_Request.priority_ = lambda self: self.slo.priority  # type: ignore


class _Replica:
    """Dispatcher-side view of one replica process (plain struct; all
    mutation happens under the fleet condition variable)."""

    __slots__ = ("label", "proc", "sock", "rx", "in_flight", "ready_info",
                 "alive", "ctrl", "quarantined", "last_rx", "last_ping",
                 "ping_sent", "ping_seq", "ewma", "breaker",
                 "breaker_until", "probe", "txlock")

    def __init__(self, label: str, proc) -> None:
        self.label = label
        self.proc = proc
        self.sock: Optional[Socket] = None
        self.rx: Optional[threading.Thread] = None
        self.in_flight: Optional[_Request] = None
        self.ready_info: Optional[dict] = None
        self.alive = False
        # replica-bound lifecycle control frames (load/activate/retire):
        # dispatched ahead of queued traffic, never rerouted to a peer
        self.ctrl: deque = deque()
        # set by an op="quarantine" frame (arena checksum divergence):
        # the death that follows is a quarantine, not a crash
        self.quarantined: Optional[str] = None
        # --- degraded-network state (mutated under the fleet cv, except
        # last_rx which any rx frame stamps — a GIL-atomic float store)
        self.last_rx = 0.0                       # monotonic of last frame
        self.last_ping = 0.0                     # monotonic of last ping
        self.ping_sent: Dict[int, float] = {}    # seq -> send monotonic
        self.ping_seq = 0
        self.ewma: Optional[float] = None        # send->result EWMA
        self.breaker = "closed"                  # closed|open|half_open
        self.breaker_until = 0.0                 # open -> probe allowed at
        self.probe = False                       # half-open probe out
        # heartbeat pings share the socket with dispatch sends from other
        # threads; two interleaved sendalls would shear a frame.  Held
        # across the wire by contract -> serial for the lockdep witness
        self.txlock = _lockdep.mark_serial(threading.Lock())


_ERR_TYPES = {"ValueError": ValueError, "KeyError": KeyError,
              "TimeoutError": TimeoutError, "TypeError": TypeError}


_EBADF_ONLY = (errno.EBADF,)
_SHUTDOWN_BENIGN = (errno.EBADF, errno.EPIPE, errno.ECONNRESET)


def _note_os(e: OSError, site: str, benign=()) -> None:
    """Classify an OS error unless its errno is expected on this path
    (EBADF from closing an already-closed socket at shutdown, EPIPE to a
    dead replica): xtb_resource_errors_total exists to surface the errno
    that MATTERS, and steady shutdown noise would bury it."""
    if getattr(e, "errno", None) not in benign:
        _resources.note_os_error(e, site)


class ServingFleet:
    """Spawn, route, survive.  ``models`` maps name -> Booster or model
    path (published into the store at start); alternatively pass a
    pre-populated ``store_dir`` and ``models=None``.

    Usage::

        from xgboost_tpu.serving import ServingFleet, SLOClass

        with ServingFleet({"ctr": booster}, n_replicas=4,
                          cache_dir="/var/cache/xtb-fleet") as fleet:
            y = fleet.predict("ctr", rows)                  # numpy path
            y = fleet.predict_arrow("ctr", record_batch)    # arrow path
    """

    def __init__(self, models: Optional[Dict[str, Any]] = None,
                 config: Optional[FleetConfig] = None, **overrides) -> None:
        if config is None:
            config = FleetConfig(**overrides)
        elif overrides:
            config = dataclasses.replace(config, **overrides)
        self.config = config
        self._models = dict(models or {})
        self._ins = _Instruments.get()
        self._cv = threading.Condition()
        self._queue = DispatchQueue(config.max_queue)
        self._admit = AdaptiveAdmission(config.max_queue)
        self._ins.admission_window.set(self._admit.limit())
        self._replicas: Dict[str, _Replica] = {}
        self._failures: List[Tuple[str, int, str]] = []
        self._err_files: Dict[str, str] = {}
        # observability plane (all under _cv): last shipped registry
        # snapshot + flight ring per replica label — retained after death
        # (the merged /metrics view and the postmortem dump read these)
        self._telemetry: Dict[str, dict] = {}
        self._flight_rings: Dict[str, list] = {}
        self._flight_dumps: Dict[str, str] = {}
        # label -> reason for every replica that quarantined itself after
        # a failed arena verification (retained after death, like the
        # telemetry above — the postmortem surface)
        self.quarantined: Dict[str, str] = {}
        self._next_id = itertools.count(1)
        # lifecycle state (all under _cv): the fleet's view of each model's
        # active version (labels unversioned latency) and per-model shadow
        # routing config {name: {"version", "every", "n", stats...}}
        self._versions: Dict[str, int] = {}
        self._shadow: Dict[str, dict] = {}
        # online-loop state (under _cv): per-model feedback sample rate
        # (resynced onto respawns like _versions) and the registered
        # driver-side consumer of decoded feedback records
        self._sampling: Dict[str, int] = {}
        self._feedback_sink = None
        # consumer for op="label" frames from label-feed connections
        # (signature sink(trace, y)); the online loop registers
        # FeedbackHub.label here
        self._label_sink = None
        # recent send->result predict latencies (under _cv): the sample
        # the hedge-budget quantile is computed from
        self._lat_hist: deque = deque(maxlen=512)
        self._respawned = 0
        self._started = False
        self._bringup_done = False
        self._closed = False
        self._extinct = False  # every replica dead, respawn budget spent
        self._listener: Optional[Socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._sched_thread: Optional[threading.Thread] = None
        self._store_dir: Optional[str] = None
        self._tmp_store = False
        # --- sharded front-end state (docs/serving.md "Sharded
        # topology").  With n_shards > 1 THIS instance becomes a pure
        # router: start() builds one single-shard sibling ServingFleet
        # per shard (each with its own listener, queue, cv, rx threads,
        # and degraded-network state — shared-nothing by construction;
        # the store/cache dirs and the telemetry registry stay shared)
        # and submit() routes by shard_of(model, tenant).  The list is
        # immutable once start() returns, so routing reads it lock-free.
        self._shards: Optional[List["ServingFleet"]] = None
        self._label_prefix = ""     # "s<k>:" on a shard, "" unsharded
        self._shard_label = "0"     # {shard=} label on per-shard series
        self._ext_listener: Optional[Socket] = None  # pre-bound listener
        # SO_REUSEPORT accept path: label-prefix -> owning shard, shared
        # by every sibling so an accept landing on the wrong shard's
        # listener hands the connection to its owner
        self._shard_peers: Optional[Dict[str, "ServingFleet"]] = None

    # ---------------------------------------------------------------- start
    def start(self) -> "ServingFleet":
        import socket as socketlib

        from .modelstore import ModelStore

        if self.config.n_shards > 1:
            return self._start_sharded()
        with self._cv:
            if self._started:
                return self
            self._started = True
            self._store_dir = self.config.store_dir
            if self._store_dir is None:
                self._store_dir = tempfile.mkdtemp(prefix="xtb_fleet_store_")
                self._tmp_store = True
        # opt-in scrape endpoint (XGBOOST_TPU_METRICS_PORT): one GET
        # /metrics returns driver-side xtb_fleet_* plus every replica's
        # shipped series, per-process-labeled and merged
        _distributed.start_metrics_server()
        # default-on wall sampler: the dispatcher rx/dispatch loops join
        # the merged flame view (telemetry/profiler.py)
        _profiler.maybe_start("fleet-driver")
        if _trace.active():
            _trace.set_process_name("fleet-driver")
        store = ModelStore(self._store_dir)
        for name, source in self._models.items():
            store.publish(name, source)
        if not store.entries():
            raise ValueError("fleet has no models: pass models= or a "
                             "pre-populated store_dir=")
        with self._cv:
            try:
                # commit the serving versions explicitly (one rewrite,
                # no-op when already committed): once a fleet runs,
                # "active" never silently tracks "latest", so a lifecycle
                # publish (which bumps latest) cannot move what serves
                # before its activate commit
                store.commit_active()
            except OSError as e:
                # read-only store: a pure-read consumer fleet still works
                # (lifecycle publishes need a writable store anyway, so
                # "latest" cannot drift underneath this fleet)
                warnings.warn(f"model store {self._store_dir} is not "
                              f"writable ({e}); serving versions stay "
                              f"implicitly latest-tracking")
            for name, version in store.serving_entries():
                self._versions[name] = version
        listener = self._ext_listener
        if listener is None:
            listener = socketlib.socket()
            listener.bind(("127.0.0.1", 0))
            listener.listen(max(8, self.config.n_replicas * 2))
        with self._cv:
            self._listener = listener
        accept = threading.Thread(target=self._accept_loop, daemon=True,
                                  name="xtb-fleet-accept")
        sched = threading.Thread(target=self._dispatch_loop, daemon=True,
                                 name="xtb-fleet-dispatch")
        with self._cv:
            self._accept_thread = accept
            self._sched_thread = sched
        for i in range(self.config.n_replicas):
            self._spawn(f"{self._label_prefix}replica{i}")
        accept.start()
        sched.start()
        deadline = time.monotonic() + self.config.ready_timeout_s
        with self._cv:
            while True:
                ready = sum(1 for r in self._replicas.values() if r.alive)
                remaining = deadline - time.monotonic()
                if (ready >= self.config.n_replicas or self._closed
                        or self._extinct or remaining <= 0):
                    # extinct = every replica already crashed and the
                    # respawn budget is spent: fail NOW, not at timeout
                    failures = list(self._failures)
                    break
                self._cv.wait(timeout=min(remaining, 0.5))
        if ready < self.config.n_replicas:
            self._shutdown()
            raise WorkerFailedError(
                f"fleet start: only {ready}/{self.config.n_replicas} "
                f"replicas became ready within "
                f"{self.config.ready_timeout_s}s", failures)
        with self._cv:
            self._bringup_done = True
        return self

    def _start_sharded(self) -> "ServingFleet":
        """Bring up the shared-nothing sharded topology: publish the
        models ONCE into the (shared) store, then build and start one
        single-shard sibling fleet per shard concurrently.  Each sibling
        owns its replica group end to end — listener, DispatchQueue,
        heartbeat/breaker/hedge state, rx threads, its own cv lock — so
        shards never contend on a shared dispatcher lock; only the mmap
        store, the warm compile cache, the process-wide governor, and the
        telemetry registry (per-shard series separated by the ``shard=``
        label and shard-prefixed replica labels) are shared."""
        import socket as socketlib

        from .modelstore import ModelStore

        cfg = self.config
        with self._cv:
            if self._started:
                return self
            self._started = True
            self._store_dir = cfg.store_dir
            if self._store_dir is None:
                self._store_dir = tempfile.mkdtemp(prefix="xtb_fleet_store_")
                self._tmp_store = True
        store = ModelStore(self._store_dir)
        for name, source in self._models.items():
            store.publish(name, source)
        if not store.entries():
            raise ValueError("fleet has no models: pass models= or a "
                             "pre-populated store_dir=")
        try:
            store.commit_active()
        except OSError as e:
            warnings.warn(f"model store {self._store_dir} is not "
                          f"writable ({e}); serving versions stay "
                          f"implicitly latest-tracking")
        n = cfg.n_shards
        listeners: Optional[List[Socket]] = None
        if cfg.reuseport and hasattr(socketlib, "SO_REUSEPORT"):
            # every shard listens on ONE shared port: the kernel spreads
            # incoming replica connections across the shard listeners,
            # and an accept that lands on the wrong shard is handed to
            # its owner by hello-label prefix (_accept_loop)
            listeners = []
            port = 0
            for _ in range(n):
                s = socketlib.socket()
                s.setsockopt(socketlib.SOL_SOCKET,
                             socketlib.SO_REUSEPORT, 1)
                s.bind(("127.0.0.1", port))
                port = s.getsockname()[1]
                s.listen(max(8, cfg.n_replicas * 2))
                listeners.append(s)
        shards: List[ServingFleet] = []
        for k in range(n):
            sub = dataclasses.replace(
                cfg, n_shards=1, n_replicas=cfg.n_replicas // n,
                store_dir=self._store_dir)
            shard = ServingFleet(None, sub)
            shard._label_prefix = f"s{k}:"
            shard._shard_label = str(k)
            if listeners is not None:
                shard._ext_listener = listeners[k]
            shards.append(shard)
        if listeners is not None:
            peers = {f"s{k}": shards[k] for k in range(n)}
            for shard in shards:
                shard._shard_peers = peers
        with self._cv:
            self._shards = shards
        self._ins.shards.set(float(n))
        errs: List[BaseException] = []

        def _boot(shard: "ServingFleet") -> None:
            try:
                shard.start()
            except BaseException as e:  # surfaced to the caller below
                errs.append(e)

        threads = [threading.Thread(target=_boot, args=(s,), daemon=True,
                                    name=f"xtb-fleet-boot-s{i}")
                   for i, s in enumerate(shards)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            self.close()
            raise errs[0]
        with self._cv:
            self._bringup_done = True
        return self

    def _spawn(self, label: str) -> None:
        port = self._listener.getsockname()[1]
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        plat = self.config.platform
        if plat is None:
            try:
                import jax

                plat = jax.default_backend()
            except Exception:
                plat = None
        if plat == "cpu" and self.config.nthread_per_replica > 0:
            # N replicas each spawning an ncores-wide spinning XLA intra-op
            # pool convoy each other off the host (4 replicas on 2 cores
            # measured ~10x per-request inflation); one knob caps BOTH
            # pools — the native XtbThreadPool (--nthread) and XLA's —
            # at the configured per-replica width.  This REPLACES any
            # inherited XLA_FLAGS for CPU replicas (set
            # nthread_per_replica=0 to pass the parent's flags through);
            # on other backends replicas inherit the environment as-is.
            env["XLA_FLAGS"] = (
                "--xla_cpu_multi_thread_eigen=false "
                f"intra_op_parallelism_threads="
                f"{self.config.nthread_per_replica}")
        argv = [sys.executable, "-m", "xgboost_tpu.serving.replica",
                "--host", "127.0.0.1", "--port", str(port),
                "--store", self._store_dir, "--label", label,
                "--nthread", str(self.config.nthread_per_replica)]
        if self.config.cache_dir:
            argv += ["--cache", self.config.cache_dir]
        if self.config.platform:
            argv += ["--platform", self.config.platform]
        if self.config.warmup_buckets:
            argv += ["--buckets",
                     ",".join(str(b) for b in self.config.warmup_buckets)]
        proc = spawn_worker(argv, label, self._err_files, env=env)
        with self._cv:
            self._replicas[label] = _Replica(label, proc)

    # ------------------------------------------------------------- accepting
    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except OSError as e:
                # listener closed = shutdown (EBADF, not worth counting);
                # anything else (EMFILE under fd exhaustion) is
                # classified before we stop accepting
                _note_os(e, "fleet.accept", benign=_EBADF_ONLY)
                return
            wire.configure(sock)
            try:
                sock.settimeout(self.config.ready_timeout_s)
                hello, _ = wire.recv_frame(sock)
                if hello.get("kind") == "label_feed":
                    # not a replica: a label producer (possibly another
                    # process/host) streaming op="label" frames for the
                    # online loop's join — its own rx thread, no replica
                    # bookkeeping
                    sock.settimeout(None)
                    src = str(hello.get("label", "labeler"))
                    threading.Thread(
                        target=self._label_rx_loop, args=(src, sock),
                        daemon=True,
                        name=f"xtb-fleet-label-{src}").start()
                    continue
                ready, _ = wire.recv_frame(sock)
                sock.settimeout(None)
                label = hello.get("label", "?")
            except (wire.WireError, TimeoutError):
                # malformed or slow hello (socket.timeout is
                # TimeoutError): not a resource event
                sock.close()
                continue
            except OSError as e:
                _note_os(e, "fleet.handshake")
                sock.close()
                continue
            # SO_REUSEPORT accept path: the kernel may spread replica
            # connections across the shard listeners, so the one that
            # landed here can belong to a sibling — the hello label's
            # shard prefix names the owner; registration happens there,
            # under the OWNER's cv
            owner = self
            if self._shard_peers is not None and ":" in label:
                owner = self._shard_peers.get(label.split(":", 1)[0], self)
            owner._register_replica(label, sock, ready)

    def _register_replica(self, label: str, sock, ready: dict) -> None:
        """Adopt one post-handshake replica connection: bookkeeping,
        respawn resync control frames, rx thread.  Factored out of
        :meth:`_accept_loop` because under the SO_REUSEPORT accept path
        the accepting thread may be a sibling shard's — every mutation
        here is under THIS shard's cv, whichever thread runs it."""
        rx = threading.Thread(target=self._rx_loop, args=(label, sock),
                              daemon=True, name=f"xtb-fleet-rx-{label}")
        with self._cv:
            rep = self._replicas.get(label)
            if rep is None or self._closed:
                sock.close()
                return
            rep.sock = sock
            rep.rx = rx
            rep.ready_info = ready
            rep.alive = True
            # liveness baseline: the ready frame is frame zero, so a
            # replica that acks ready and then never answers anything
            # trips the heartbeat deadline instead of coasting to the
            # global one; last_ping = now delays the first ping by one
            # full heartbeat period
            rep.last_rx = rep.last_ping = time.monotonic()
            self._ins.breaker_state.labels(label).set(0.0)
            # version resync for RESPAWNS: the replica read the
            # manifest's active versions at process startup, which may
            # predate an activate committed while it was warming up
            # (spawn -> set_active -> broadcast that skipped the
            # not-yet-ready respawn).  Idempotent activate frames,
            # dispatched ahead of any traffic, bring it to the fleet's
            # view; when the replica already serves that version this
            # is a no-op pin.  Initial bring-up needs none of this:
            # start() returns only after every replica is ready, so no
            # activate can precede an initial replica's manifest read.
            for name, version in (self._versions.items()
                                  if self._bringup_done else ()):
                rid = next(self._next_id)
                rep.ctrl.append(_Request(
                    rid, name, {"op": "activate", "model": name,
                                "version": int(version), "id": rid},
                    b"", self.config.default_slo))
            # feedback-capture resync, same contract as the version
            # resync above: a respawn that missed the sample broadcast
            # converges to the fleet's configured rate
            for name, every in (self._sampling.items()
                                if self._bringup_done else ()):
                rid = next(self._next_id)
                rep.ctrl.append(_Request(
                    rid, name, {"op": "sample", "model": name,
                                "every": int(every), "id": rid},
                    b"", self.config.default_slo))
            self._ins.replicas.set(
                sum(1 for r in self._replicas.values() if r.alive))
            self._cv.notify_all()
        self._ins.coldstart.labels(
            ready.get("cache_state", "cold")).observe(
            float(ready.get("warmup_s", 0.0)))
        rx.start()

    # ------------------------------------------------------------ rx per rep
    def _rx_loop(self, label: str, sock) -> None:
        # buffered frame source: one GIL release/reacquire per frame
        # instead of three — the reacquire under a many-threaded
        # dispatcher was profiled at ~ms of convoy per request
        stream = wire.reader(sock)
        budget = _frame_budget_s()
        # rx occupancy: seconds spent PROCESSING frames vs blocked in
        # recv, accumulated per dispatcher shard.  busy/wall is the
        # shard's rx-loop busy fraction — the saturation bench reads it
        # to prove the dispatcher (not the load generator or replicas)
        # is/isn't the ceiling (docs/observability.md).
        busy = self._ins.shard_rx_busy.labels(self._shard_label)
        t_resume = 0.0
        while True:
            if t_resume:
                busy.inc(time.monotonic() - t_resume)
            try:
                header, payload = wire.recv_frame(stream, budget_s=budget,
                                                  peer=label)
            except (wire.WireError, OSError) as e:
                if isinstance(e, wire.WireCorruptError):
                    # corrupt replica->dispatcher frame: the death path
                    # below IS the quarantine — record it as one (the
                    # replica-receive direction counts its own side)
                    from ..reliability import integrity as _integrity

                    _integrity.quarantined("wire")
                    _flight.record("fault", "fleet.wire_corrupt",
                                   replica=label)
                self._on_replica_death(label, e)
                return
            t_resume = time.monotonic()
            rep_rx = self._replicas.get(label)
            if rep_rx is not None:
                # any frame proves the replica end-to-end alive: stamp the
                # liveness clock (GIL-atomic float store, no lock needed)
                rep_rx.last_rx = time.monotonic()
            op = header.get("op")
            if op == wire.PONG:
                self._on_pong(label, header)
                continue
            if op == wire.TELEMETRY:
                # unsolicited shipment from the replica's serve loop: it
                # does NOT complete the in-flight request — ingest and go
                # straight back to the socket
                self._ingest_telemetry(label, payload)
                continue
            if op == wire.FEEDBACK:
                # unsolicited like telemetry: a sampled request's features
                # + served scores for the online loop; never completes the
                # in-flight request
                self._ingest_feedback(label, header, payload)
                continue
            if op == "quarantine":
                # the replica's loaded arena checksum diverged: it fences
                # itself and dies right after this frame.  Record WHY so
                # the imminent death path (EOF on this socket) reads as a
                # quarantine, not an unexplained crash; in-flight work
                # reroutes through the normal death machinery.
                reason = str(header.get("error", "arena checksum diverged"))
                with self._cv:
                    rep = self._replicas.get(label)
                    if rep is not None:
                        rep.quarantined = reason
                    self.quarantined[label] = reason
                from ..reliability import integrity as _integrity

                _integrity.quarantined("arena")
                _flight.record("event", "fleet.replica_quarantined",
                               replica=label, error=reason)
                continue
            # one critical section per completion: free the replica AND
            # claim its next request.  The hot path never notifies the cv —
            # per-request notify_all wakes the housekeeping thread (which
            # polls every replica process) and convoys every rx thread on
            # the lock; profiled as the fleet=4 throughput collapse.
            nxt = None
            expired: List[_Request] = []
            with self._cv:
                rep = self._replicas.get(label)
                req = rep.in_flight if rep is not None else None
                if rep is not None:
                    rep.in_flight = None
                    if rep.alive and not self._closed:
                        # replica-bound control frames dispatch ahead of
                        # queued traffic (a swap must not starve behind a
                        # deep queue; predicts already on the wire keep
                        # their ordering — that IS the drain contract)
                        if rep.ctrl:
                            nxt = rep.ctrl.popleft()
                        elif self._breaker_free(rep, time.monotonic()):
                            nxt, expired = self._queue.pop(time.monotonic())
                        if nxt is not None:
                            rep.in_flight = nxt
                            if rep.breaker == "half_open":
                                rep.probe = True
            self._expire(expired)
            if nxt is not None:
                # next request on the wire BEFORE this result's caller is
                # woken: the replica computes while the client-side wake
                # and copy-out happen, instead of idling through them
                self._send(rep, nxt)
            if req is None or header.get("id") != req.id:
                continue  # late/unmatched frame (e.g. post-reroute twin)
            if op == "result":
                if req.header.get("op") == "predict" and req.t_send_ns:
                    # send->result latency for the EWMA/breaker and the
                    # hedge-budget quantile (stamped BEFORE the send, so
                    # tx-side link degradation counts against the replica)
                    self._net_observe(
                        label,
                        (time.perf_counter_ns() - req.t_send_ns) / 1e9)
                shape = tuple(int(x) for x in header["shape"])
                arr = np.frombuffer(payload, np.float32).reshape(shape)
                self._finish(req, arr)
            elif op == "ctrl_ok":
                self._finish_ctrl(req, header)
            else:
                etype = _ERR_TYPES.get(header.get("etype", ""), RuntimeError)
                self._fail(req, etype(header.get("error", "replica error")))

    def _label_rx_loop(self, source: str, sock) -> None:
        """One label-feed connection: decode each ``op="label"`` frame
        (trace id + float32 labels) and hand it to the registered sink —
        the online loop's FeedbackHub.label, whose bounded symmetric
        join counts every drop.  Best-effort like feedback ingest: a
        malformed frame or sink error is recorded and dropped, never
        fatal — the serving plane must not depend on a label producer."""
        stream = wire.reader(sock)
        budget = _frame_budget_s()
        while True:
            try:
                header, payload = wire.recv_frame(stream, budget_s=budget,
                                                  peer=source)
            except wire.WireError:
                break  # producer gone (EOF/corrupt/slow-loris): drop it
            except OSError as e:
                # same verdict, but a socket-level failure gets classified
                # (ENOSPC/EMFILE here would otherwise surface three
                # subsystems away as a mystery)
                _resources.note_os_error(e, "fleet.label_rx")
                break
            op = header.get("op")
            if op == "close":
                break
            if op != wire.LABEL:
                continue  # unknown op on a label feed: ignore
            self._ins.label_frames.inc()
            try:
                trace = header.get("trace")
                y = np.frombuffer(payload, np.float32)
            except (TypeError, ValueError) as e:
                _flight.record("fault", "fleet.label_decode",
                               source=source, error=str(e))
                continue
            with self._cv:
                sink = self._label_sink
            if sink is None:
                continue
            try:
                sink(trace, y)
            except Exception as e:  # a broken consumer must not kill rx
                _flight.record("fault", "fleet.label_sink",
                               source=source, error=str(e))
        try:
            sock.close()
        except OSError as e:
            _note_os(e, "fleet.sock_close", benign=_EBADF_ONLY)

    def _ingest_feedback(self, label: str, header: dict, payload) -> None:
        """One replica feedback frame: decode the (features, scores) pair
        and hand it to the registered sink.  Malformed frames and sink
        errors are dropped with a flight fault, never fatal — feedback is
        a best-effort measurement stream, the serving plane must not
        depend on its consumer."""
        try:
            R, F = (int(x) for x in header["shape"])
            X = np.frombuffer(payload[:R * F * 4],
                              np.float32).reshape(R, F)
            scores = np.frombuffer(payload[R * F * 4:], np.float32)
            oshape = header.get("oshape")
            if oshape:
                scores = scores.reshape([int(x) for x in oshape])
            model = str(header.get("model"))
            trace = header.get("trace")
        except (KeyError, TypeError, ValueError) as e:
            _flight.record("fault", "fleet.feedback_decode", replica=label,
                           error=str(e))
            return
        self._ins.feedback_frames.labels(model).inc()
        self._ins.feedback_rows.labels(model).inc(float(R))
        with self._cv:
            sink = self._feedback_sink
        if sink is None:
            return
        try:
            sink({"model": model, "trace": trace, "X": X,
                  "scores": scores, "replica": label})
        except Exception as e:  # a broken consumer must not kill rx
            _flight.record("fault", "fleet.feedback_sink", replica=label,
                           error=str(e))

    def _ingest_telemetry(self, label: str, payload) -> None:
        """One replica telemetry frame: retain the latest snapshot +
        flight ring under the replica's label and feed the merged view
        (snapshot, flight ring, and profiler stacks — ingest_payload
        keeps all three per source for /flight and the merged flame)."""
        try:
            data = json.loads(bytes(payload))
        except (ValueError, TypeError):
            return  # a malformed shipment is dropped, never fatal
        snap = data.get("snapshot")
        ring = data.get("flight") or []
        with self._cv:
            if snap:
                self._telemetry[label] = snap
            self._flight_rings[label] = ring
        _distributed.get_merged().ingest_payload(label, data)

    def _finish(self, req: _Request, arr: np.ndarray) -> None:
        req.state = "done"
        if req.future.set_running_or_notify_cancel():
            if req.hedge:
                # the twin beat the original to the shared future
                self._ins.hedge_wins.inc()
            req.future.set_result(arr)
            if _trace.active() and req.header.get("trace"):
                # dispatcher-side bracket of the whole request: with the
                # replica's own replica.execute event (same trace id) the
                # merged timeline shows dispatch -> queue -> execute ->
                # reply per request
                now = time.perf_counter_ns()
                _trace.emit("fleet.request", req.t_submit_ns,
                            now - req.t_submit_ns,
                            trace=req.header["trace"], model=req.model)
            # only delivered results count: an abandoned (caller-timed-out,
            # cancelled) request's latency would skew the histogram
            lat = time.monotonic() - req.t_submit
            # the request's trace id rides as a bucket exemplar: the
            # /metrics scrape names the exact request behind the window's
            # max latency per bucket ("what was the p99"), resolvable
            # against the flight recorder / merged chrome trace
            self._ins.latency.labels(req.model).observe(
                lat, exemplar=req.header.get("trace"))
            self._admit_ok()
            # per-version latency: explicit version from the header, else
            # the fleet's view of the model's active version — the
            # lifecycle comparator reads candidate vs incumbent from here
            v = req.header.get("version")
            if v is None:
                v = self._versions.get(req.model)
            if v is not None:
                self._ins.version_latency.labels(
                    req.model, str(v)).observe(lat)
        elif req.hedge or req.hedged:
            # the pair's other side already settled the caller: this
            # duplicate result is the waste a hedge knowingly pays for
            self._ins.hedge_wasted.inc()

    def _finish_ctrl(self, req: _Request, header: dict) -> None:
        """A replica acked a lifecycle control frame: the future carries
        the ack payload (aot hit/compile counts, seconds)."""
        req.state = "done"
        if req.future.set_running_or_notify_cancel():
            req.future.set_result(dict(header))

    def _fail(self, req: _Request, exc: BaseException) -> None:
        req.state = "done"
        if req.future.set_running_or_notify_cancel():
            req.future.set_exception(exc)

    # --------------------------------------------------- adaptive admission
    def _admit_pressure(self) -> None:
        """One overload signal (shed / expiry / replica death): AIMD
        multiplicative decrease; on the onto-the-floor edge, declare
        overload to the resource governor — the SLO brownout starts."""
        edge = self._admit.on_pressure()
        self._ins.admission_window.set(self._admit.limit())
        if edge:
            _resources.get_governor().degrade(
                "overload", "fleet admission window at floor")
            _resources.degraded_event(
                "fleet", "admission_floor", window=self._admit.limit())

    def _admit_ok(self) -> None:
        """One completed request: additive increase; on the recovered
        edge, lift the governor's overload level again."""
        recovered = self._admit.on_ok()
        self._ins.admission_window.set(self._admit.limit())
        if recovered:
            _resources.get_governor().restore("overload")

    def _expire(self, expired: List[_Request]) -> None:
        """Fail requests whose class deadline passed while queued."""
        for r in expired:
            self._ins.deadline.labels(r.slo.name).inc()
            self._admit_pressure()
            self._fail(r, TimeoutError(
                f"request {r.id} ({r.model}) expired in queue after "
                f"{r.slo.deadline_s}s (slo={r.slo.name})"))

    # ----------------------------------------------------------- death path
    def _on_replica_death(self, label: str, cause: BaseException) -> None:
        with self._cv:
            rep = self._replicas.pop(label, None)
            if rep is None:
                return
            closed = self._closed
            req = rep.in_flight
            rep.in_flight = None
            rep.alive = False
            ctrl_orphans = list(rep.ctrl)
            rep.ctrl.clear()
            self._ins.replicas.set(
                sum(1 for r in self._replicas.values() if r.alive))
            # a dead replica's breaker is moot: park the gauge at closed
            # so the label doesn't read as permanently ejected
            self._ins.breaker_state.labels(label).set(0.0)
            if (req is not None and not closed
                    and req.header.get("op") != "predict"):
                # a replica-bound control frame cannot reroute to a peer:
                # fail it — the broadcast layer tolerates this, because a
                # respawn reads the committed store state at startup
                ctrl_orphans.append(req)
                req = None
            if req is not None and not closed:
                # the dead replica's batch: requeue at the front (predict
                # is idempotent; the twin result from the corpse, if any,
                # is dropped by the id check in _rx_loop)
                req.tries += 1
                if req.tries <= 3:
                    self._queue.requeue_front(req)
                    self._ins.rerouted.inc()
                    req = None
            respawn = (not closed
                       and self._respawned < self.config.max_respawns)
            if respawn:
                self._respawned += 1
                n = self._respawned
            self._cv.notify_all()
        try:
            rep.sock and rep.sock.close()
        except OSError as e:
            _note_os(e, "fleet.sock_close", benign=_EBADF_ONLY)
        rc = rep.proc.poll()
        if not closed:
            # a real death is an overload signal too: the survivors
            # briefly have less capacity (a clean shutdown's EOFs are us
            # closing sockets, not pressure)
            self._admit_pressure()
        tail = stderr_tail(self._err_files.get(label, ""))
        if rep.quarantined:
            tail = f"[quarantined: {rep.quarantined}]\n{tail}"
        if not closed:
            # a real death gets a postmortem; a clean shutdown's EOFs are
            # us closing the sockets, not replicas dying
            dump_path = self._dump_replica_flight(label, rc)
            if dump_path:
                tail += f"\n[flight recorder: {dump_path}]"
            _flight.record("event", "fleet.replica_death", replica=label,
                           exit=rc if rc is not None else -1)
        with self._cv:
            self._failures.append((label, rc if rc is not None else -1,
                                   tail))
        for c in ctrl_orphans:
            self._fail(c, WorkerFailedError(
                f"replica {label} died before completing control op "
                f"{c.header.get('op')!r} (exit={rc}): {cause}",
                [(label, rc if rc is not None else -1, tail)]))
        if req is not None:
            self._fail(req, WorkerFailedError(
                f"request {req.id} lost to replica {label} "
                f"{req.tries} times (exit={rc}): {cause}",
                [(label, rc if rc is not None else -1, tail)]))
        if req is None and not closed:
            self._pump()  # the requeued request goes to a live replica now
        if respawn:
            self._ins.respawns.inc()
            self._spawn(f"{self._label_prefix}respawn{n}")
        elif not self._alive_or_pending():
            # fleet extinct: nothing will ever drain the queue — fail what
            # is queued AND mark the fleet so later submits fail fast
            # instead of queueing into a hang
            failures = list(self._failures)
            with self._cv:
                self._extinct = True
                dead = self._queue.drain()
                self._cv.notify_all()
            err = WorkerFailedError(
                "every fleet replica died and the respawn budget is spent",
                failures)
            for r in dead:
                self._fail(r, err)

    def _dump_replica_flight(self, label: str, rc) -> Optional[str]:
        """Postmortem for a dead replica, written DRIVER-side from the
        last telemetry shipment: the replica's recent flight ring plus
        its final registry snapshot — present even for SIGKILL, which
        leaves the corpse no chance to dump anything itself.  The path
        lands in :attr:`flight_dumps` and on the failure record."""
        with self._cv:
            ring = list(self._flight_rings.get(label, ()))
            snap = self._telemetry.get(label)
        path = os.path.join(_flight.dump_dir(),
                            f"flight_fleet_{label}_{os.getpid()}.json")
        try:
            tmp = f"{path}.tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({"label": label, "exit": rc, "events": ring,
                           "snapshot": snap, "dumped_by": "dispatcher"},
                          fh)
            os.replace(tmp, path)
        except OSError as e:  # pragma: no cover - fs trouble must not
            _resources.note_os_error(e, "fleet.flight_dump")
            return None       # block the death path
        with self._cv:
            self._flight_dumps[label] = path
        return path

    @property
    def flight_dumps(self) -> Dict[str, str]:
        """label -> postmortem path for every dead replica; on a sharded
        fleet, merged across shards (prefixed labels never collide)."""
        if self._shards is not None:
            out: Dict[str, str] = {}
            for sh in self._shards:
                with sh._cv:
                    out.update(sh._flight_dumps)
            return out
        with self._cv:
            return dict(self._flight_dumps)

    def _alive_or_pending(self) -> bool:
        with self._cv:
            return any(r.proc.poll() is None or r.alive
                       for r in self._replicas.values())

    # ------------------------------------------------------------ dispatching
    def _dispatch_loop(self) -> None:
        """Housekeeping only: reap pre-ready crashes and run a periodic
        fallback pump.  The hot path never waits on this thread — requests
        go to replicas directly from the thread that created the work or
        the capacity (:meth:`_pump`), because a per-request hand-off
        through one scheduler thread costs two GIL/condvar wake hops per
        request and caps fleet throughput at single-replica rates."""
        while True:
            with self._cv:
                if self._closed:
                    return
                self._reap_locked()
                self._cv.wait(timeout=0.2)
                if self._closed:
                    return
            # governor tick: the fleet process's ONLY poll site — it is
            # what walks an errno-raised disk/fd level back down once
            # real headroom recovers (internally rate-limited), ending a
            # brownout instead of latching it for the process lifetime
            _resources.get_governor().poll(self._store_dir)
            self._net_tick()
            self._pump()

    def _pump(self) -> None:
        """Dispatch queued requests onto free replicas until one side runs
        dry.  Called wherever work or capacity appears: submit(), the rx
        loop on completion, the death path after a requeue, and the
        housekeeping loop.  Safe from any number of threads at once — the
        pop and the replica in_flight claim are one critical section, so
        two pumpers can never double-assign; the socket send runs outside
        the lock."""
        while True:
            with self._cv:
                if self._closed:
                    return
                now = time.monotonic()
                req, expired = (None, [])
                target = None
                free = [r for r in self._replicas.values()
                        if r.alive and r.in_flight is None]
                # replica-bound control frames first (they cannot be
                # served by any other replica and must not starve; the
                # breaker never gates them — an ejected replica still
                # takes lifecycle ops)
                for r in free:
                    if r.ctrl:
                        req = r.ctrl.popleft()
                        target = r
                        break
                if req is None and free:
                    admit = [r for r in free if self._breaker_free(r, now)]
                    if admit:
                        req, expired = self._queue.pop(now)
                        target = admit[0] if req is not None else None
                if req is not None:
                    target.in_flight = req
                    if target.breaker == "half_open":
                        target.probe = True
            self._expire(expired)
            if req is None:
                return
            self._send(target, req)

    def _send(self, rep: _Replica, req: _Request) -> None:
        try:
            spec = _faults.maybe_inject("fleet.dispatch")
        except _faults.FaultInjected as e:
            with self._cv:
                rep.in_flight = None
                self._cv.notify_all()
            self._fail(req, e)
            return
        if spec is not None and spec.kind == "drop_connection":
            # sever the chosen replica's socket (in_flight already carries
            # this request): the rx loop sees EOF and runs the death path,
            # which requeues the request onto a surviving replica
            try:
                rep.sock.shutdown(2)
            except OSError as e:
                # severing an already-dead socket is the point here
                _note_os(e, "fleet.sock_close", benign=_SHUTDOWN_BENIGN)
            return
        try:
            # stamp BEFORE the send: tx-side link degradation (jitter,
            # throttling) must count against the replica's measured
            # latency, or the breaker could never see a slow outbound link
            req.t_send_ns = time.perf_counter_ns()
            with rep.txlock:
                wire.send_frame(rep.sock, req.header, req.payload,
                                peer=rep.label)
            if req.header.get("op") == "predict":
                self._ins.requests.labels(req.model).inc()
                # per-shard throughput attribution: the bench divides
                # Δrows by wall to report rows/s per dispatcher shard
                self._ins.shard_requests.labels(self._shard_label).inc()
                shape = req.header.get("shape")
                if shape:
                    self._ins.shard_rows.labels(self._shard_label).inc(
                        float(shape[0]))
                if _trace.active() and req.header.get("trace"):
                    # queue-time bracket: submit -> on-the-wire (re-emitted
                    # per try when a reroute requeues the request)
                    _trace.emit("fleet.queue", req.t_submit_ns,
                                req.t_send_ns - req.t_submit_ns,
                                trace=req.header["trace"], model=req.model,
                                replica=rep.label)
        except OSError as e:
            self._on_replica_death(rep.label, e)

    # ------------------------------------- degraded-network survival plane
    def _set_breaker(self, rep: _Replica, state: str) -> None:
        """Transition a replica's circuit breaker (cv held): state,
        gauge, transition counter, flight event."""
        if rep.breaker == state:
            return
        rep.breaker = state
        rep.probe = False
        self._ins.breaker_transitions.labels(state).inc()
        self._ins.breaker_state.labels(rep.label).set(
            {"closed": 0.0, "open": 1.0, "half_open": 2.0}[state])
        _flight.record("event", "fleet.breaker", replica=rep.label,
                       state=state)

    def _breaker_free(self, rep: _Replica, now: float) -> bool:
        """Whether the breaker lets this replica take queued predicts
        (cv held).  Walks open -> half-open once the cooldown elapses;
        half-open admits at most ONE outstanding probe — the caller that
        claims the replica marks ``rep.probe``."""
        if self.config.breaker_latency_s <= 0:
            return True
        if rep.breaker == "open" and now >= rep.breaker_until:
            self._set_breaker(rep, "half_open")
        if rep.breaker == "open":
            return False
        if rep.breaker == "half_open" and rep.probe:
            return False
        return True

    def _net_observe(self, label: str, lat: float) -> None:
        """One send->result predict latency: feed the hedge-budget
        sample, update the replica's EWMA, and run the breaker state
        machine (docs/reliability.md "Degraded networks")."""
        thresh = self.config.breaker_latency_s
        with self._cv:
            self._lat_hist.append(lat)
            rep = self._replicas.get(label)
            if rep is None:
                return
            rep.ewma = lat if rep.ewma is None else (
                0.2 * lat + 0.8 * rep.ewma)
            if thresh <= 0:
                return
            if rep.breaker == "half_open":
                # this result IS the probe's verdict
                if lat <= thresh:
                    rep.ewma = lat  # the probe is the new baseline
                    self._set_breaker(rep, "closed")
                else:
                    rep.breaker_until = (time.monotonic()
                                         + self.config.breaker_cooldown_s)
                    self._set_breaker(rep, "open")
            elif rep.breaker == "closed" and rep.ewma > thresh:
                rep.breaker_until = (time.monotonic()
                                     + self.config.breaker_cooldown_s)
                self._set_breaker(rep, "open")

    def _on_pong(self, label: str, header: dict) -> None:
        """A replica answered a heartbeat: close out the matching ping,
        observe the application-level round trip, and — when the
        replica's breaker is waiting on a probe no traffic will ever
        send it — let the pong BE the probe.  This is a network breaker:
        the RTT rides the same degraded rx path a predict result would,
        and without it an ejected replica whose siblings absorb all
        traffic would stay ejected forever (readmission must not depend
        on starving the healthy replicas first)."""
        now = time.monotonic()
        with self._cv:
            rep = self._replicas.get(label)
            if rep is None:
                return
            try:
                t0 = rep.ping_sent.pop(int(header.get("seq", -1)), None)
            except (TypeError, ValueError):
                t0 = None
            rtt = (now - t0) if t0 is not None else None
            if (rtt is not None and self.config.breaker_latency_s > 0
                    and not rep.probe):
                if rep.breaker == "open" and now >= rep.breaker_until:
                    self._set_breaker(rep, "half_open")
                if rep.breaker == "half_open":
                    if rtt <= self.config.breaker_latency_s:
                        rep.ewma = rtt  # the probe is the new baseline
                        self._set_breaker(rep, "closed")
                    else:
                        rep.breaker_until = (
                            now + self.config.breaker_cooldown_s)
                        self._set_breaker(rep, "open")
        if rtt is not None:
            self._ins.hb_rtt.labels(label).observe(rtt)

    def _hedge_budget_locked(self) -> Optional[float]:
        """Quantile-derived hedge budget (cv held): the configured
        quantile of recent send->result latencies, floored at
        ``hedge_min_s``.  None = hedging off or not enough history yet
        (a cold fleet must not hedge off noise)."""
        q = self.config.hedge_quantile
        if q <= 0.0 or len(self._lat_hist) < 8:
            return None
        lats = sorted(self._lat_hist)
        idx = min(len(lats) - 1, int(q * len(lats)))
        return max(lats[idx], self.config.hedge_min_s)

    def _net_tick(self) -> None:
        """Degraded-network housekeeping, run from the dispatch loop's
        0.2s cadence: schedule heartbeat pings, declare half-open
        replicas dead (no pong AND no other frame past the deadline),
        and hedge in-flight predicts past the quantile budget onto free
        replicas.  All state decisions under the cv; every socket write
        outside it."""
        cfg = self.config
        now = time.monotonic()
        pings: List[_Replica] = []
        dead: List[str] = []
        hedges: List[Tuple[_Replica, _Request]] = []
        with self._cv:
            if self._closed:
                return
            for rep in self._replicas.values():
                if not rep.alive or rep.sock is None:
                    continue
                if (cfg.heartbeat_s > 0
                        and now - rep.last_ping >= cfg.heartbeat_s):
                    rep.last_ping = now
                    rep.ping_seq += 1
                    rep.ping_sent[rep.ping_seq] = now
                    pings.append(rep)
                if (cfg.heartbeat_timeout_s > 0 and rep.ping_sent
                        and (now - min(rep.ping_sent.values())
                             > cfg.heartbeat_timeout_s)
                        and now - rep.last_rx > cfg.heartbeat_timeout_s):
                    # half-open or wedged: the oldest ping went
                    # unanswered AND nothing else arrived either.  TCP
                    # keepalive cannot see this (the tx direction still
                    # works); EOF never comes (the process is alive).
                    dead.append(rep.label)
            budget = self._hedge_budget_locked()
            if budget is not None:
                spare = [r for r in self._replicas.values()
                         if r.alive and r.in_flight is None
                         and r.label not in dead
                         and self._breaker_free(r, now)]
                for rep in list(self._replicas.values()):
                    if not spare:
                        break  # hedging is bounded to spare capacity
                    req = rep.in_flight
                    if (req is None or rep.label in dead
                            or req.header.get("op") != "predict"
                            or req.hedge or req.hedged
                            or not req.t_send_ns):
                        continue
                    age = (time.perf_counter_ns() - req.t_send_ns) / 1e9
                    if age <= budget:
                        continue
                    # twin: fresh id (the rx id check drops whichever
                    # result loses), SHARED future (first settle wins —
                    # replicas are deterministic, so the winner's bytes
                    # equal the loser's and hedging stays bitwise-neutral)
                    twin_id = next(self._next_id)
                    hdr = dict(req.header)
                    hdr["id"] = twin_id
                    hdr["hedge"] = True  # replica skips feedback capture
                    twin = _Request(twin_id, req.model, hdr, req.payload,
                                    req.slo)
                    twin.future = req.future
                    twin.hedge = True
                    twin.state = "inflight"
                    req.hedged = True
                    tgt = spare.pop(0)
                    tgt.in_flight = twin
                    if tgt.breaker == "half_open":
                        tgt.probe = True
                    hedges.append((tgt, twin))
        for rep in pings:
            try:
                with rep.txlock:
                    wire.send_frame(rep.sock, {"op": wire.PING,
                                               "seq": rep.ping_seq},
                                    peer=rep.label)
            except OSError as e:
                self._on_replica_death(rep.label, e)
        for label in dead:
            _flight.record("fault", "fleet.half_open", replica=label)
            self._on_replica_death(label, TimeoutError(
                f"replica {label}: no pong and no frame within "
                f"{cfg.heartbeat_timeout_s}s (half-open or wedged link)"))
        for tgt, twin in hedges:
            self._ins.hedges.inc()
            _flight.record("event", "fleet.hedge", replica=tgt.label,
                           id=twin.id, model=twin.model)
            self._send(tgt, twin)

    # ------------------------------------------------------------------ API
    def submit(self, model: str, X=None, *, arrow=None,
               tenant: Optional[str] = None, output_margin: bool = False,
               version: Optional[int] = None) -> Future:
        """Queue one predict; returns a Future of the result rows.  Pass
        ``X`` (numpy, raw path) or ``arrow`` (pyarrow RecordBatch/Table —
        or pre-encoded IPC bytes, forwarded untouched)."""
        if (X is None) == (arrow is None):
            raise ValueError("pass exactly one of X= or arrow=")
        if self._shards is not None:
            # sharded front-end: pure-hash client-side partitioning —
            # the owning shard runs the WHOLE admission path (brownout,
            # AIMD window, shed, shadow) against its own state
            shard = self._shards[shard_of(model, tenant,
                                          len(self._shards))]
            return shard.submit(model, X, arrow=arrow, tenant=tenant,
                                output_margin=output_margin,
                                version=version)
        slo = self.config.resolve_slo(tenant)
        # resource-pressure brownout BEFORE any other work — including
        # the payload encode, which is exactly the CPU/memory cost a
        # degraded host cannot spare: under pressure (overload / memory /
        # disk / fd), low-SLO tenants are refused on the tenant name
        # alone — deterministic cutoff per governor level
        # (docs/reliability.md "Resource pressure & graceful
        # degradation"); higher classes keep their full service
        cutoff = _resources.get_governor().brownout_cutoff()
        if cutoff is not None and slo.priority < cutoff:
            self._ins.brownout.labels(slo.name).inc()
            fut: Future = Future()
            fut.set_exception(QueueFullError(
                f"browned out: resource pressure level "
                f"{_resources.get_governor().max_level()} sheds "
                f"slo={slo.name} (priority {slo.priority} < cutoff "
                f"{cutoff})"))
            return fut
        if X is not None:
            fields, payload = wire.encode_raw(np.asarray(X))
        elif isinstance(arrow, (bytes, bytearray, memoryview)):
            fields, payload = {"enc": wire.ARROW}, memoryview(arrow)
        else:
            fields, payload = wire.encode_arrow(arrow)
        # everything but the queue push happens outside the cv (the lock is
        # the fleet's one contended resource; hot-path critical sections
        # stay tiny and notify-free)
        rid = next(self._next_id)  # itertools.count is atomic
        header = dict(fields)
        # the request's trace id, born here and carried on the wire: the
        # replica tags its replica.execute event with it, so one merged
        # trace shows the whole dispatch->queue->execute->reply path
        header.update({"op": "predict", "id": rid, "model": model,
                       "margin": bool(output_margin),
                       "trace": f"{os.getpid():x}-{rid:x}"})
        if version is not None:
            header["version"] = int(version)
        req = _Request(rid, model, header, payload, slo)
        # the trace id rides on the future too: feedback capture keys its
        # samples off it, so a label producer can join labels to requests
        # (hub.label(fut.trace_id, y)) without a side channel
        req.future.trace_id = header["trace"]
        shadow_req = None
        with self._cv:
            if self._closed:
                raise RuntimeError("ServingFleet is closed")
            if not self._started:
                raise RuntimeError("ServingFleet.start() has not run")
            if self._extinct:
                raise WorkerFailedError(
                    "every fleet replica died and the respawn budget is "
                    "spent", list(self._failures))
            sh = self._shadow.get(model) if version is None else None
            if sh is not None:
                # deterministic 1-in-N selection (a counter, not a PRNG:
                # replayable, and exactly the configured fraction)
                sh["n"] += 1
                if sh["n"] % sh["every"] == 0 and cutoff is not None:
                    # any brownout level sheds the twin (priority -2^31
                    # < every cutoff): the discretionary duplicate load
                    # is the FIRST thing a degraded host stops paying
                    self._ins.brownout.labels(_SHADOW_SLO.name).inc()
                elif sh["n"] % sh["every"] == 0:
                    shadow_header = dict(header)
                    shadow_header["id"] = next(self._next_id)
                    shadow_header["version"] = sh["version"]
                    shadow_header["trace"] = header["trace"] + "-shadow"
                    # same payload buffer: the twin rides zero-copy too
                    shadow_req = _Request(shadow_header["id"], model,
                                          shadow_header, payload,
                                          _SHADOW_SLO)
            limit = self._admit.limit()
            victims = [self._queue.push(req, limit=limit)]
            if shadow_req is not None:
                victims.append(self._queue.push(shadow_req, limit=limit))
        if shadow_req is not None:
            self._attach_shadow(model, req, shadow_req)
        for victim in victims:
            if victim is None:
                continue
            self._ins.shed.labels(victim.slo.name).inc()
            self._admit_pressure()
            self._fail(victim, QueueFullError(
                f"fleet queue full (admission window {limit} of "
                f"{self.config.max_queue}); shed slo={victim.slo.name} "
                f"request {victim.id}"))
        self._pump()  # a free replica takes this request on OUR thread
        return req.future

    def predict(self, model: str, X, *, tenant: Optional[str] = None,
                output_margin: bool = False, version: Optional[int] = None,
                timeout: Optional[float] = None) -> np.ndarray:
        """Blocking predict through the fleet (numpy request path)."""
        slo = self.config.resolve_slo(tenant)
        fut = self.submit(model, X, tenant=tenant,
                          output_margin=output_margin, version=version)
        return self._wait(fut, timeout, slo, model)

    def predict_arrow(self, model: str, batch, *,
                      tenant: Optional[str] = None,
                      output_margin: bool = False,
                      version: Optional[int] = None,
                      timeout: Optional[float] = None) -> np.ndarray:
        """Blocking predict with an Arrow RecordBatch/Table (or IPC
        bytes): the zero-copy request path."""
        slo = self.config.resolve_slo(tenant)
        fut = self.submit(model, arrow=batch, tenant=tenant,
                          output_margin=output_margin, version=version)
        return self._wait(fut, timeout, slo, model)

    def _wait(self, fut: Future, timeout: Optional[float], slo: SLOClass,
              model: str) -> np.ndarray:
        if timeout is None:
            timeout = slo.deadline_s
        try:
            return fut.result(timeout=timeout)
        except FuturesTimeout:
            fut.cancel()
            self._ins.deadline.labels(slo.name).inc()
            raise TimeoutError(
                f"predict({model!r}) missed its {timeout}s deadline "
                f"(slo={slo.name})") from None

    # ----------------------------------------------------- lifecycle control
    @property
    def store_dir(self) -> Optional[str]:
        """The fleet's model-store directory (the lifecycle manager's
        publish target)."""
        return self._store_dir

    def _control_all(self, fields: Dict[str, Any],
                     timeout: float = 300.0) -> List[dict]:
        """Broadcast one control frame to every live replica and collect
        the acks.  A replica that DIES mid-broadcast is tolerated — its
        respawn reads the committed store state at startup and converges —
        but an error *reply* (bad version, refused retire) raises."""
        pending: List[Tuple[str, _Request]] = []
        fields = dict(fields)
        # one trace id per broadcast (lifecycle CycleReports reference it;
        # replicas log it with the applied control op)
        fields.setdefault(
            "trace", f"ctrl-{os.getpid():x}-{next(self._next_id):x}")
        _flight.record("event", f"fleet.{fields.get('op')}",
                       model=str(fields.get("model")),
                       version=fields.get("version"),
                       trace=fields["trace"])
        with self._cv:
            if not self._started or self._closed:
                raise RuntimeError("ServingFleet is not running")
            for rep in self._replicas.values():
                if not rep.alive:
                    continue
                rid = next(self._next_id)
                header = dict(fields)
                header["id"] = rid
                req = _Request(rid, str(fields.get("model", "?")), header,
                               b"", self.config.default_slo)
                rep.ctrl.append(req)
                pending.append((rep.label, req))
        if not pending:
            raise WorkerFailedError(
                "no live replica to broadcast to", list(self._failures))
        self._pump()
        acks: List[dict] = []
        for label, req in pending:
            try:
                acks.append(req.future.result(timeout=timeout))
            except WorkerFailedError:
                with self._cv:
                    gone = label not in self._replicas
                if not gone:  # pragma: no cover - defensive
                    raise
        return acks

    def load_version(self, model: str, version: int,
                     timeout: float = 300.0,
                     trace: Optional[str] = None) -> List[dict]:
        """Double-buffer a published store version onto every replica:
        registry entry, arch-keyed AOT warm attach, fast path, NaN warm
        pass — all while the incumbent keeps serving.  Returns per-replica
        acks carrying aot_hits/aot_compiled (a same-architecture
        continuation shows hits, not compiles)."""
        if self._shards is not None:
            trace = trace or self._broadcast_trace()
            return [a for sh in self._shards
                    for a in sh.load_version(model, version, timeout,
                                             trace)]
        fields = {"op": "load", "model": model, "version": int(version)}
        if trace:
            fields["trace"] = trace
        return self._control_all(fields, timeout)

    def activate_version(self, model: str, version: int,
                         timeout: float = 300.0,
                         trace: Optional[str] = None) -> List[dict]:
        """Repoint ``model``'s unversioned traffic at ``version``.

        Durably commits the store manifest FIRST (``set_active``), then
        broadcasts: a replica that dies between the two reads the
        committed version when it respawns, so the fleet converges on the
        new version through any single failure.  Per replica the activate
        frame is serialized after every previously dispatched predict —
        nothing is dropped, and no request observes a half-swap."""
        from .modelstore import ModelStore

        if self._shards is not None:
            # each shard runs the full commit-first sequence itself;
            # set_active is idempotent under the manifest flock, and the
            # per-shard _versions update keeps each shard's respawn
            # resync frames correct
            trace = trace or self._broadcast_trace()
            return [a for sh in self._shards
                    for a in sh.activate_version(model, version, timeout,
                                                 trace)]
        ModelStore(self._store_dir).set_active(model, int(version))
        with self._cv:
            # fleet view moves WITH the durable commit, before the
            # broadcast: a replica respawning while the acks are collected
            # builds its ready-time resync frames from _versions, and a
            # stale entry here would regress it to the old version
            self._versions[model] = int(version)
        fields = {"op": "activate", "model": model, "version": int(version)}
        if trace:
            fields["trace"] = trace
        return self._control_all(fields, timeout)

    def retire_version(self, model: str, version: int,
                       timeout: float = 300.0,
                       trace: Optional[str] = None) -> List[dict]:
        """Drop a non-active version from every replica.  The retire frame
        rides each replica's serialized connection, so it executes only
        after every predict dispatched before it has drained; replicas
        refuse to retire the active version."""
        if self._shards is not None:
            trace = trace or self._broadcast_trace()
            return [a for sh in self._shards
                    for a in sh.retire_version(model, version, timeout,
                                               trace)]
        fields = {"op": "retire", "model": model, "version": int(version)}
        if trace:
            fields["trace"] = trace
        return self._control_all(fields, timeout)

    def _broadcast_trace(self) -> str:
        """One trace id shared by a sharded broadcast's per-shard legs,
        so lifecycle CycleReports and replica logs correlate the whole
        fan-out as one operation."""
        return f"ctrl-{os.getpid():x}-{next(self._next_id):x}"

    def active_version(self, model: str) -> Optional[int]:
        if self._shards is not None:
            return self._shards[0].active_version(model)
        with self._cv:
            return self._versions.get(model)

    def scrub_replicas(self, timeout: float = 300.0) -> List[dict]:
        """Broadcast an on-demand arena scrub: every live replica
        re-verifies each RESIDENT version's checksum against the store
        meta (the same check its periodic ``XGBOOST_TPU_ARENA_SCRUB_S``
        tick runs).  Healthy replicas ack ``{"verified": n}``; a replica
        whose loaded checksum diverges sends an ``op="quarantine"`` frame
        and dies — its in-flight batch reroutes and :attr:`quarantined`
        records the reason.  Riding the serialized connection means the
        scrub drains behind every predict dispatched before it."""
        if self._shards is not None:
            return [a for sh in self._shards
                    for a in sh.scrub_replicas(timeout)]
        return self._control_all({"op": "scrub", "model": "*"}, timeout)

    def quarantined_replicas(self) -> Dict[str, str]:
        """label -> reason for every self-quarantined replica (retained
        after death)."""
        if self._shards is not None:
            out: Dict[str, str] = {}
            for sh in self._shards:
                out.update(sh.quarantined_replicas())
            return out
        with self._cv:
            return dict(self.quarantined)

    # ------------------------------------------------------ feedback capture
    def set_sampling(self, model: str, every: int,
                     timeout: float = 300.0) -> List[dict]:
        """Broadcast the feedback-capture rate for ``model``: every live
        replica samples 1-in-``every`` of its unversioned requests
        (deterministically, keyed off the request-id half of the trace id)
        and ships features + served scores back as ``op="feedback"``
        frames.  ``every=0`` turns capture off.  Respawned replicas are
        resynced like versions, so the configured rate survives deaths."""
        every = int(every)
        if every < 0:
            raise ValueError(f"every must be >= 0, got {every}")
        if self._shards is not None:
            return [a for sh in self._shards
                    for a in sh.set_sampling(model, every, timeout)]
        with self._cv:
            if every > 0:
                self._sampling[model] = every
            else:
                self._sampling.pop(model, None)
        return self._control_all(
            {"op": "sample", "model": model, "every": every}, timeout)

    def set_feedback_sink(self, sink) -> None:
        """Register the driver-side consumer of decoded feedback records
        (dicts with model/trace/X/scores/replica), called on rx threads.
        ``None`` unregisters.  Sink exceptions are contained (flight
        fault), not propagated into the rx loop."""
        if self._shards is not None:
            for sh in self._shards:
                sh.set_feedback_sink(sink)
            return
        with self._cv:
            self._feedback_sink = sink

    def sampling_rate(self, model: str) -> int:
        """The configured feedback-capture rate (0 = off)."""
        if self._shards is not None:
            return self._shards[0].sampling_rate(model)
        with self._cv:
            return self._sampling.get(model, 0)

    def set_label_sink(self, sink) -> None:
        """Register the consumer for ``op="label"`` frames arriving over
        label-feed connections (called ``sink(trace, y)`` on the feed's
        rx thread).  The online loop registers ``FeedbackHub.label``
        here, so labels produced in another process/host join the same
        bounded symmetric join as in-process ones.  ``None``
        unregisters; sink exceptions are contained like feedback's."""
        if self._shards is not None:
            for sh in self._shards:
                sh.set_label_sink(sink)
            return
        with self._cv:
            self._label_sink = sink

    def label_endpoint(self) -> Tuple[str, int]:
        """(host, port) a label producer connects to — the fleet's frame
        listener.  Open the channel with :func:`wire.label_feed` and
        stream labels with :func:`wire.send_label`.  On a sharded fleet
        this is shard 0's listener (every shard accepts label feeds and
        the sink is fanned out, so any shard's endpoint works)."""
        if self._shards is not None:
            return self._shards[0].label_endpoint()
        if self._listener is None:
            raise RuntimeError("fleet not started: no listener yet")
        host, port = self._listener.getsockname()[:2]
        return str(host), int(port)

    # ------------------------------------------------------- shadow scoring
    def set_shadow(self, model: str, version: int,
                   fraction: float) -> None:
        """Mirror a deterministic ``fraction`` of ``model``'s unversioned
        traffic onto candidate ``version`` (which must be loaded).  The
        twin's result never reaches a caller; the comparator feeds
        ``xtb_lifecycle_shadow_*`` and per-version latency series."""
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"shadow fraction must be in (0, 1], "
                             f"got {fraction}")
        if self._shards is not None:
            # a model's traffic spans shards (tenant is part of the
            # routing key): every shard mirrors its own slice, stats
            # merge on read
            for sh in self._shards:
                sh.set_shadow(model, version, fraction)
            return
        every = max(1, round(1.0 / fraction))
        with self._cv:
            self._shadow[model] = {
                "version": int(version), "every": every, "n": 0,
                "pairs": 0, "failures": 0, "sum_div": 0.0, "max_div": 0.0,
                "sum_ks": 0.0, "max_ks": 0.0,
                "sum_psi": 0.0, "max_psi": 0.0,
                "sum_cal": 0.0, "max_cal": 0.0,
            }

    @staticmethod
    def _shadow_summary(sh: dict) -> dict:
        pairs = sh["pairs"]
        return {"pairs": pairs, "failures": sh["failures"],
                "mean_div": (sh["sum_div"] / pairs) if pairs else 0.0,
                "max_div": sh["max_div"],
                "mean_ks": (sh["sum_ks"] / pairs) if pairs else 0.0,
                "max_ks": sh["max_ks"],
                "mean_psi": (sh["sum_psi"] / pairs) if pairs else 0.0,
                "max_psi": sh["max_psi"],
                "mean_cal": (sh["sum_cal"] / pairs) if pairs else 0.0,
                "max_cal": sh["max_cal"]}

    @staticmethod
    def _merge_shadow_raw(raws: List[dict]) -> Optional[dict]:
        """Fold per-shard shadow accumulators into one: sums add, maxes
        max — the summary derives means from the folded sums."""
        if not raws:
            return None
        out = dict(raws[0])
        for r in raws[1:]:
            for k in ("pairs", "failures", "sum_div", "sum_ks",
                      "sum_psi", "sum_cal"):
                out[k] += r[k]
            for k in ("max_div", "max_ks", "max_psi", "max_cal"):
                out[k] = max(out[k], r[k])
        return out

    def clear_shadow(self, model: str) -> Optional[dict]:
        """Stop mirroring; returns the accumulated comparator stats
        (pairs, failures, mean/max divergence and KS) or None if never
        set.  On a sharded fleet the per-shard accumulators merge into
        one summary."""
        if self._shards is not None:
            raws = []
            for shard in self._shards:
                with shard._cv:
                    raw = shard._shadow.pop(model, None)
                if raw is not None:
                    raws.append(raw)
            merged = self._merge_shadow_raw(raws)
            return None if merged is None else self._shadow_summary(merged)
        with self._cv:
            sh = self._shadow.pop(model, None)
        if sh is None:
            return None
        return self._shadow_summary(sh)

    def shadow_stats(self, model: str) -> Optional[dict]:
        if self._shards is not None:
            raws = []
            for shard in self._shards:
                with shard._cv:
                    raw = shard._shadow.get(model)
                    if raw is not None:
                        raws.append(dict(raw))
            merged = self._merge_shadow_raw(raws)
            return None if merged is None else self._shadow_summary(merged)
        with self._cv:
            sh = self._shadow.get(model)
            if sh is None:
                return None
            return self._shadow_summary(sh)

    def _attach_shadow(self, model: str, primary: _Request,
                       shadow: _Request) -> None:
        """Compare the pair once BOTH futures settle (runs on whichever rx
        thread finishes second; cheap: one mean-abs-diff)."""
        remaining = [2]
        lock = threading.Lock()

        def done(_fut):
            with lock:
                remaining[0] -= 1
                if remaining[0]:
                    return
            self._compare_shadow(model, primary, shadow)

        primary.future.add_done_callback(done)
        shadow.future.add_done_callback(done)

    def _compare_shadow(self, model: str, primary: _Request,
                        shadow: _Request) -> None:
        sh_live = None
        try:
            a = np.asarray(primary.future.result(timeout=0), np.float64)
            b = np.asarray(shadow.future.result(timeout=0), np.float64)
            if a.shape == b.shape:
                div = float(np.mean(np.abs(a - b)))
                ks = _ks_stat(a, b)
                psi = _psi(a, b)
                cal = _calibration_gap(a, b)
            else:
                div = ks = psi = cal = float("inf")
        except BaseException:
            self._ins.shadow_failures.labels(model).inc()
            with self._cv:
                sh_live = self._shadow.get(model)
                if sh_live is not None:
                    sh_live["failures"] += 1
            return
        self._ins.shadow_requests.labels(model).inc()
        self._ins.shadow_divergence.labels(model).observe(div)
        self._ins.shadow_ks.labels(model).observe(min(ks, 1.0))
        self._ins.shadow_psi.labels(model).observe(
            min(psi, _PSI_BUCKETS[-1] * 10))
        self._ins.shadow_calibration.labels(model).observe(
            min(cal, _SHADOW_BUCKETS[-1] * 10))
        with self._cv:
            sh_live = self._shadow.get(model)
            if sh_live is not None:
                sh_live["pairs"] += 1
                sh_live["sum_div"] += div
                sh_live["max_div"] = max(sh_live["max_div"], div)
                sh_live["sum_ks"] += ks
                sh_live["max_ks"] = max(sh_live["max_ks"], ks)
                sh_live["sum_psi"] += psi
                sh_live["max_psi"] = max(sh_live["max_psi"], psi)
                sh_live["sum_cal"] += cal
                sh_live["max_cal"] = max(sh_live["max_cal"], cal)

    # ---------------------------------------------------------------- admin
    def replica_info(self) -> List[dict]:
        """Ready-frame info per live replica (warmup_s, aot hit/compile
        counts, cache_state) — the cold-start telemetry."""
        if self._shards is not None:
            return [info for sh in self._shards for info in sh.replica_info()]
        with self._cv:
            return [dict(r.ready_info) for r in self._replicas.values()
                    if r.alive and r.ready_info]

    def alive_replicas(self) -> int:
        if self._shards is not None:
            return sum(sh.alive_replicas() for sh in self._shards)
        with self._cv:
            return sum(1 for r in self._replicas.values() if r.alive)

    def queue_depth(self) -> int:
        if self._shards is not None:
            return sum(sh.queue_depth() for sh in self._shards)
        with self._cv:
            return len(self._queue)

    def _reap_locked(self) -> None:
        """Catch replicas that died without a socket event (pre-connect
        crash, kill -9 before EOF surfaces).  Caller holds the cv."""
        dead = [r.label for r in self._replicas.values()
                if r.proc.poll() is not None and not r.alive
                and r.sock is None]
        for label in dead:
            # run the death path without the lock held
            threading.Thread(target=self._on_replica_death,
                             args=(label, RuntimeError(
                                 "replica exited before ready")),
                             daemon=True).start()

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        if self._shards is not None:
            # shards first (they own the sockets and subprocesses), in
            # parallel — each is an independent single-shard fleet
            ts = [threading.Thread(target=sh.close, daemon=True)
                  for sh in self._shards]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            if self._tmp_store and self._store_dir:
                import shutil

                shutil.rmtree(self._store_dir, ignore_errors=True)
            return
        self._shutdown()

    def _shutdown(self) -> None:
        with self._cv:
            self._closed = True
            dead = self._queue.drain()
            reps = list(self._replicas.values())
            for rep in reps:  # pending control frames cannot complete now
                dead.extend(rep.ctrl)
                rep.ctrl.clear()
            self._cv.notify_all()
        err = RuntimeError("ServingFleet closed")
        for r in dead:
            self._fail(r, err)
        if self._sched_thread is not None:
            self._sched_thread.join(timeout=5)
        for rep in reps:
            if rep.sock is not None:
                try:
                    with rep.txlock:
                        wire.send_frame(rep.sock, {"op": "close"})
                except OSError as e:
                    _note_os(e, "fleet.shutdown",
                             benign=_SHUTDOWN_BENIGN)
        deadline = time.monotonic() + 10
        for rep in reps:
            while rep.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if rep.proc.poll() is None:
                rep.proc.kill()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError as e:
                _note_os(e, "fleet.shutdown", benign=_EBADF_ONLY)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
        for rep in reps:
            if rep.sock is not None:
                try:
                    rep.sock.close()
                except OSError as e:
                    _note_os(e, "fleet.sock_close", benign=_EBADF_ONLY)
        for path in self._err_files.values():
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            except OSError as e:
                _resources.note_os_error(e, "fleet.shutdown")
        if self._tmp_store and self._store_dir:
            import shutil

            shutil.rmtree(self._store_dir, ignore_errors=True)

    def __enter__(self) -> "ServingFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
