"""ServingEngine: batched multi-model inference with latency SLOs.

The production predict path the ROADMAP north star asks for.  Three ideas:

1. **Pre-compiled bucket programs.**  Requests pad to power-of-two row
   buckets (ops/predict.py ``bucket_rows``) and run through the same jitted
   entry points training eval uses, so the compiled-program cache is shared
   engine-wide and — after ``warmup()`` — steady-state traffic never traces.
   ``compile_cache_size()`` is the regression gauge: it must not grow once
   warm (the test suite asserts this under an N-thread hammer).
2. **Dynamic micro-batching.**  Concurrent callers coalesce per
   (model, version, options) key up to ``max_batch`` rows / ``max_delay_us``
   (batcher.py), one worker executes, results split per caller.  This is how
   the engine sidesteps both the embedded-CPython C-ABI GIL serialization
   (docs/serving.md) and JAX dispatch contention: threads cost one batch.
3. **Hot-model residency.**  Snapshots live in a ModelRegistry with LRU
   eviction + version pinning; stacked tree tensors stay device-resident for
   the model's residency lifetime (registry.py).

On accelerator backends the engine donates a per-(model, bucket) scratch
buffer into each call so XLA writes margins into recycled device memory
(steady state allocates nothing per request); CPU ignores donation, so the
path self-disables there.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, Optional, Tuple

import numpy as np

from ..ops.predict import (_MIN_ROW_BUCKET, _POW2_ROW_CEILING, bucket_rows,
                           pad_rows, predict_cache_size)
from ..telemetry import span
from .batcher import MicroBatcher
from .metrics import ServingMetrics
from .registry import ModelRegistry
from .snapshot import InferenceSnapshot


@dataclasses.dataclass
class ServeConfig:
    """SLO knobs (docs/serving.md has the tuning guide)."""

    max_batch: int = 4096        # admission: batch launches at this many rows
    max_delay_us: int = 2000     # admission: ... or when the oldest waited this
    max_models: int = 8          # LRU residency cap (registry)
    # row buckets compiled up front; None = every bucket the ADMISSION policy
    # can produce (<= max_batch rows), so default-config batched traffic never
    # compiles at steady state.  A single request LARGER than max_batch runs
    # as its own oversized batch and still compiles on first hit — warm its
    # bucket explicitly if such requests are part of the SLO.  An explicit
    # tuple trades warm-up time for first-hit compiles.
    warmup_buckets: Optional[Tuple[int, ...]] = None
    use_batcher: bool = True     # False = every predict() runs inline
    donate_buffers: bool = True  # donate scratch on non-CPU backends
    # degradation knobs (docs/reliability.md): a per-request deadline in
    # seconds (None = wait forever) — predict() raises TimeoutError instead
    # of outliving its SLO on a slow/stuck batch; and a bound on queued rows
    # — beyond it submit() sheds (QueueFullError, xtb_serve_shed_total)
    # instead of growing an unbounded backlog
    request_timeout_s: Optional[float] = None
    max_queue_rows: Optional[int] = None

    def __post_init__(self) -> None:
        if self.max_batch < 1 or self.max_delay_us < 0:
            raise ValueError("max_batch >= 1 and max_delay_us >= 0 required")
        if self.request_timeout_s is not None and self.request_timeout_s <= 0:
            raise ValueError("request_timeout_s must be positive or None")
        if self.max_queue_rows is not None and self.max_queue_rows < 1:
            raise ValueError("max_queue_rows must be >= 1 or None")

    def resolved_warmup_buckets(self) -> Tuple[int, ...]:
        if self.warmup_buckets is not None:
            return self.warmup_buckets
        top = bucket_rows(self.max_batch)
        out, b = [], _MIN_ROW_BUCKET
        while b < min(top, _POW2_ROW_CEILING):
            out.append(b)
            b *= 2
        while b < top:  # past the pow2 ceiling buckets step by the ceiling
            out.append(b)
            b += _POW2_ROW_CEILING
        out.append(top)
        return tuple(out)


class _Program:
    """Per-snapshot compiled-call wrapper holding the donation scratch."""

    def __init__(self, snap: InferenceSnapshot, donate: bool) -> None:
        import jax

        self.snap = snap
        self.donate = donate and jax.default_backend() != "cpu"
        self._scratch = {}  # bucket -> recycled (B, K) device buffer
        # donated-path callers hold this from margin_padded through their
        # host copy-out: the buffer pushed to _scratch is the CALLER'S result,
        # so a second thread (warmup racing the batcher worker on the same
        # program) must not pop and donate it until the caller has drained it.
        # Re-entrant: margin_padded/base_dev guard their own stores while a
        # caller already holds the drain-scope lock
        self.donate_lock = threading.RLock()
        self.seen_shapes = set()  # (bucket, F, margin) served at least once
        self._base_dev = None
        if self.donate:  # pragma: no cover - accelerator-only path
            def _margin_into(scratch, Xp):
                del scratch  # memory-only donation: XLA reuses the buffer
                return snap.margin_padded(Xp)

            self._fn = jax.jit(_margin_into, donate_argnums=(0,))

    def base_dev(self):
        if self._base_dev is None:
            import jax.numpy as jnp

            with self.donate_lock:
                if self._base_dev is None:
                    self._base_dev = jnp.asarray(self.snap.base_score)
        return self._base_dev

    def margin_padded(self, Xp, donate: bool = True):
        if not (self.donate and donate):
            return self.snap.margin_padded(Xp)
        import jax.numpy as jnp  # pragma: no cover - accelerator-only path

        B = Xp.shape[0]
        with self.donate_lock:  # re-entrant under the caller's drain scope
            scratch = self._scratch.pop(B, None)
            if scratch is None:
                scratch = jnp.zeros((B, self.snap.n_groups), jnp.float32)
            out = self._fn(scratch, Xp)
            # recycle: the caller holds donate_lock until its result is
            # copied to host, so the next donated call cannot reuse this
            # buffer early
            self._scratch[B] = out
        return out


class ServingEngine:
    def __init__(self, config: Optional[ServeConfig] = None, **overrides,
                 ) -> None:
        if config is None:
            config = ServeConfig(**overrides)
        elif overrides:
            config = dataclasses.replace(config, **overrides)
        self.config = config
        self.metrics = ServingMetrics()
        self.registry = ModelRegistry(max_models=config.max_models)
        self._batcher: Optional[MicroBatcher] = (
            MicroBatcher(self._execute, max_batch=config.max_batch,
                         max_delay_us=config.max_delay_us,
                         max_queue_rows=config.max_queue_rows,
                         metrics=self.metrics)
            if config.use_batcher else None)
        self._warming = 0  # >0 while warmup() runs (attributes its compiles)
        self._warm_lock = threading.Lock()  # += / -= are not atomic
        self._prog_lock = threading.Lock()
        self._closed = False

    # ----------------------------------------------------------- model admin
    def add_model(self, name: str, source, *, version: Optional[int] = None,
                  warmup: bool = True) -> int:
        """Register a Booster (or .json/.ubj model path) and optionally
        pre-compile its warm-up bucket programs."""
        v = self.registry.register(name, source, version=version)
        if warmup:
            self.warmup(name, version=v)
        return v

    def warmup(self, name: str, version: Optional[int] = None,
               buckets: Optional[Tuple[int, ...]] = None) -> int:
        """Compile the padded-bucket programs (margin + transformed output)
        for ``buckets`` so steady-state requests never trace.  Returns the
        number of programs compiled."""
        snap, v = self.registry.get(name, version)
        before = self.compile_cache_size()
        with self._warm_lock:
            self._warming += 1
        try:
            for b in sorted(set(buckets
                                or self.config.resolved_warmup_buckets())):
                X = np.full((int(b), max(snap.num_features, 1)), np.nan,
                            np.float32)
                key = (name, v, False)
                self._execute(key, X, (snap, False))
                self._execute((name, v, True), X, (snap, True))
                prog = self._prog(snap)
                if prog.donate:  # pragma: no cover - accelerator-only path
                    # the batcher worker serves through the DONATED jit
                    # variant (a per-program cache) — compile it now too, or
                    # the first real batch per bucket pays the trace warmup
                    # was meant to absorb
                    import jax.numpy as jnp

                    with prog.donate_lock:
                        np.asarray(prog.margin_padded(jnp.asarray(X),
                                                      donate=True))
        finally:
            with self._warm_lock:
                self._warming -= 1
        compiled = self.compile_cache_size() - before
        self.metrics.compiles_warmup += compiled
        return compiled

    def pin(self, name: str, version: int) -> None:
        self.registry.pin(name, version)

    def unpin(self, name: str) -> None:
        self.registry.unpin(name)

    # -------------------------------------------------------------- predict
    def predict(self, name: str, X, *, version: Optional[int] = None,
                output_margin: bool = False, direct: bool = False,
                ) -> np.ndarray:
        """Predict rows of ``X`` with ``name`` (latest / pinned version).

        Goes through the micro-batcher unless ``direct=True`` (or the engine
        was built with ``use_batcher=False``).  Output matches
        ``Booster.predict``: (R,) for single-group models, else (R, K) —
        except DMatrix ``base_margin``, which the engine rejects (it cannot
        ride a coalesced batch; use ``Booster.predict`` for that)."""
        if self._closed:
            raise RuntimeError("ServingEngine is closed")
        t0 = time.perf_counter_ns()
        try:
            # inside the guarded region so unknown/evicted-version failures
            # land in the per-model error counter too
            snap, v = self.registry.get(name, version)
            key = (name, v, bool(output_margin))
            Xn = self._as_batch(snap, X)
            if direct or self._batcher is None:
                out = self._execute(key, Xn, (snap, output_margin))
            else:
                fut = self._batcher.submit(key, Xn, (snap, output_margin))
                try:
                    out = fut.result(timeout=self.config.request_timeout_s)
                except FuturesTimeout:
                    # deadline expired: abandon the request (cancel if it
                    # has not launched) and raise within the SLO window
                    # rather than hang on a stuck batch
                    fut.cancel()
                    self.metrics.observe_deadline(name)
                    raise TimeoutError(
                        f"predict({name!r}) missed its "
                        f"{self.config.request_timeout_s}s deadline "
                        f"(worker alive: {self._batcher.worker_alive()})"
                    ) from None
        except BaseException:
            self.metrics.observe_error(name)
            raise
        self.metrics.observe_request(name, len(Xn),
                                     time.perf_counter_ns() - t0)
        # squeeze from the OUTPUT width, not the submit-time snapshot: a
        # same-version hot-swap between submit and execute serves the new
        # snapshot, whose group count may differ from the one resolved above
        return out[:, 0] if out.shape[1] == 1 else out

    # ------------------------------------------------------------ internals
    @staticmethod
    def _as_batch(snap: InferenceSnapshot, X) -> np.ndarray:
        if hasattr(X, "host_dense"):  # DMatrix: recode cats like Booster.predict
            if getattr(X.info, "base_margin", None) is not None:
                raise ValueError(
                    "the serving engine does not apply DMatrix base_margin "
                    "(a per-request starting margin cannot ride a coalesced "
                    "batch); use Booster.predict for margin-adjusted scoring")
            X = snap.host_dense_recoded(X)
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2:
            raise ValueError(f"expected (rows, features), got shape {X.shape}")
        if snap.num_features and X.shape[1] != snap.num_features:
            raise ValueError(
                f"feature shape mismatch: model has {snap.num_features} "
                f"features, input has {X.shape[1]}")
        return X

    def _prog(self, snap: InferenceSnapshot) -> _Program:
        prog = getattr(snap, "_serve_prog", None)
        if prog is None:
            # locked check-then-set: warmup() and the batcher worker can hit
            # a fresh snapshot at once, and two _Program wrappers would mean
            # two donated jit caches and two donate_locks
            with self._prog_lock:
                prog = getattr(snap, "_serve_prog", None)
                if prog is None:
                    prog = _Program(snap, self.config.donate_buffers)
                    snap._serve_prog = prog  # rides the registry lifetime
        return prog

    def _execute(self, key: Any, X: np.ndarray, ctx) -> np.ndarray:
        """Run one (possibly coalesced) batch.  Called by the batcher worker
        or inline for direct predicts; returns host (R, K) outputs."""
        import jax.numpy as jnp

        snap, output_margin = ctx
        # re-resolve at execute time: a register() hot-swap of this (name,
        # version) between submit and execute must serve the CURRENT snapshot
        # for the whole coalesced batch, not whichever request queued first;
        # fall back to the submit-time snapshot if it was evicted meanwhile —
        # or if the replacement's feature count no longer matches this batch
        # (requests were validated against the submit-time snapshot; running
        # mismatched columns through the new trees would return garbage, JAX
        # clamps out-of-bounds feature gathers instead of erroring)
        try:
            cur, _ = self.registry.get(key[0], key[1])
            if not cur.num_features or cur.num_features == X.shape[1]:
                snap = cur
        except KeyError:
            pass
        prog = self._prog(snap)
        R = X.shape[0]
        bucket = bucket_rows(R)
        # the compile gauge walks four jit caches under the registry lock; a
        # (bucket, margin) pair this program has already served cannot compile
        # again, so skip the probe on known-warm shapes (the hot path)
        probe_key = (bucket, X.shape[1], bool(output_margin))
        probe = probe_key not in prog.seen_shapes
        before = self.compile_cache_size() if probe else 0
        Xd = pad_rows(jnp.asarray(X, dtype=jnp.float32), bucket)
        # scratch donation recycles the previous result buffer, which is only
        # safe from the single batcher worker (direct predicts from N threads
        # could donate a buffer another caller is still copying to host);
        # donate_lock is held through the host copy so a concurrent warmup()
        # on the same program cannot re-donate this result mid-drain
        on_worker = (self._batcher is not None
                     and threading.current_thread() is self._batcher._worker)
        with span("serve.execute"):
            if prog.donate and on_worker:  # pragma: no cover - accelerator-only
                with prog.donate_lock:
                    margin = prog.margin_padded(Xd, donate=True) \
                        + prog.base_dev()[None, :]
                    out = margin if output_margin else snap.transform(margin)
                    host = np.asarray(out)
            elif bucket in snap.aot_programs:
                # fleet warm path: the AOT fused serve program (warmcache)
                # — no trace, no jit-cache touch, bitwise the eager path
                host = np.asarray(snap.aot_execute(Xd, bool(output_margin)))
            else:
                margin = prog.margin_padded(Xd, donate=False) \
                    + prog.base_dev()[None, :]
                out = margin if output_margin else snap.transform(margin)
                host = np.asarray(out)
        if probe:
            # strictly positive: a concurrent eviction can shrink the gauge
            # mid-window, and a negative delta must not cancel real compiles
            grew = self.compile_cache_size() - before
            if grew > 0 and not self._warming:
                self.metrics.note_steady_compiles(grew)
            prog.seen_shapes.add(probe_key)
        return host[:R] if bucket != R else host

    # ---------------------------------------------------------------- admin
    def compile_cache_size(self) -> int:
        """Compiled predict programs alive.  Flat after warm-up == the
        no-retrace SLO holds.  The gauge is PROCESS-global (the jit cache is
        shared with training eval and any other engine), so a process that
        trains while serving can grow it — and compiles_steady — without a
        serving retrace; in mixed processes treat a bump as a prompt to
        check, not proof of regression (docs/serving.md)."""
        donated = sum(
            prog._fn._cache_size()
            for prog in self.registry.serve_programs()
            if prog.donate)  # pragma: no cover - accelerator-only term
        return predict_cache_size() + donated

    def metrics_snapshot(self) -> dict:
        snap = self.metrics.snapshot()
        snap["compiled_programs"] = self.compile_cache_size()
        snap["resident_models"] = len(self.registry)
        snap["resident_bytes"] = self.registry.resident_bytes()
        return snap

    def close(self) -> None:
        with self._warm_lock:
            if self._closed:
                return
            self._closed = True
        if self._batcher is not None:
            self._batcher.close()

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
