"""mmap-backed read-only model store: one host copy of every booster.

The fleet-scale residency problem (docs/serving.md "Fleet"): N serving
replicas each building an :class:`InferenceSnapshot` from a model file hold
N private copies of the stacked tree tensors.  The store publishes each
model ONCE as an aligned binary arena + JSON meta; replicas open the arena
with ``np.memmap`` (read-only) and the OS page cache shares the physical
pages across every process on the host — the ``data/extmem.py`` memmap
spill idea applied to model weights instead of training pages.

On the CPU backend the zero-copy goes all the way into XLA:
``jax.device_put`` of a 64-byte-aligned read-only array aliases the mapped
pages instead of copying (PJRT CPU immutable-zero-copy semantics), so M
replicas genuinely hold ONE copy of each booster's arrays in host RAM.  On
accelerator backends the arena is still the single *host* copy; each
device holds its own resident copy as usual.

Layout (``store_dir/``)::

    manifest.json          {"version": 1, "models": {name: latest_version},
                            "active": {name: serving_version}}
    <name>.v<V>.meta.json  snapshot metadata + arena field table + checksum
    <name>.v<V>.arena      64-byte-aligned concatenation of the raw arrays
    <name>.v<V>.model      Booster.serialize() bytes (lifecycle continuation)

Publishes are atomic (tmp + rename, manifest rewritten last) so a replica
opening mid-publish sees either the old or the new version, never a torn
one.  The arena stores the *snapshot* tensors (stacked node fields, group
routing, base score) — not the model file — so opening is an mmap + a few
small JSON reads, with no tree parsing on the replica cold path.

Two lifecycle additions (docs/serving.md "Online model lifecycle"):

- **Active version.**  ``manifest["active"]`` records which version is
  *serving* per name, distinct from the latest *published* one.  A hot-swap
  publishes the candidate first (latest moves, active does not) and commits
  ``set_active`` only after the validation gate passes — so a process
  killed mid-swap leaves a store whose restart serves the incumbent.
- **Model bytes + checksum.**  Each version archives the exact
  ``Booster.serialize()`` payload (continuation training resumes from
  precisely what is being served) and the meta records a SHA-256 over the
  arena fields; ``verify_checksum`` re-derives it from the mmapped arena,
  the bitwise half of the lifecycle validation gate.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import os
import tempfile
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

_ALIGN = 64  # PJRT CPU zero-copy needs 64-byte-aligned buffers
_FORMAT_VERSION = 1

try:
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX platform
    fcntl = None


class ArenaCorruptError(RuntimeError):
    """A published arena's bytes no longer match the checksum recorded at
    publish time.  Raised at replica attach (a corrupt model must never
    start serving) and by the periodic arena scrub (a replica whose loaded
    checksum diverges quarantines itself — docs/reliability.md
    "Integrity & chaos")."""


_lock_instruments = None


def _lock_ins():
    """(held gauge, wait-seconds counter) for the manifest flock — the
    observability the two-manager contention story needs: a stuck gauge
    means a wedged holder, a climbing wait counter means contention."""
    global _lock_instruments
    if _lock_instruments is None:
        from ..telemetry.registry import get_registry

        reg = get_registry()
        _lock_instruments = (
            reg.gauge("xtb_store_lock_held",
                      "manifest flocks currently held by this process"),
            reg.counter("xtb_store_lock_wait_seconds_total",
                        "seconds spent waiting to acquire the model-store "
                        "manifest flock"),
        )
    return _lock_instruments


def arena_checksum(fields: Dict[str, np.ndarray]) -> str:
    """Deterministic SHA-256 over a snapshot's field tensors (sorted key
    order; dtype + shape + raw bytes).  The same digest must come out of
    the pre-publish arrays and the post-publish mmap views — any torn or
    bit-flipped arena fails the lifecycle gate's bitwise check."""
    h = hashlib.sha256()
    for key in sorted(fields):
        arr = np.ascontiguousarray(fields[key])
        h.update(f"{key}|{arr.dtype.str}|{arr.shape}|".encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def _json_params(params: dict) -> dict:
    """The JSON-safe scalar subset of a booster's params — enough to
    rebuild the objective (`create_objective(name, params)` reads scalars
    like num_class / quantile_alpha from it)."""
    out = {}
    for k, v in params.items():
        if isinstance(v, (bool, int, float, str)) or v is None:
            out[k] = v
        elif isinstance(v, (list, tuple)) and all(
                isinstance(x, (bool, int, float, str)) for x in v):
            out[k] = list(v)
    return out


def _cat_to_json(cat_categories) -> Optional[dict]:
    if not cat_categories:
        return None
    out = {}
    for fi, vals in cat_categories.items():
        out[str(int(fi))] = [v.item() if hasattr(v, "item") else v
                             for v in list(vals)]
    return out


def _cat_from_json(obj) -> Optional[dict]:
    if not obj:
        return None
    return {int(k): list(v) for k, v in obj.items()}


class ModelStore:
    """Open (or create) a model store directory.

    Writer side: :meth:`publish` snapshots a Booster (or model path) into
    the arena format.  Reader side: :meth:`snapshot` mmaps a published
    model into an :class:`InferenceSnapshot` whose stacked tensors alias
    the store file.
    """

    def __init__(self, store_dir: str) -> None:
        self.dir = os.fspath(store_dir)
        os.makedirs(self.dir, exist_ok=True)

    # ------------------------------------------------------------- manifest
    def _manifest_path(self) -> str:
        return os.path.join(self.dir, "manifest.json")

    @contextlib.contextmanager
    def _manifest_lock(self):
        """Exclusive ``flock`` held across every manifest READ-MODIFY-WRITE
        (publish's version allocation, ``set_active``, ``commit_active``).
        Concurrent :class:`~xgboost_tpu.lifecycle.LifecycleManager`\\ s —
        threads in one process or separate processes on a shared store —
        serialize here, so two publishes can never allocate the same
        version and an activate can never overwrite a concurrent one with
        a stale manifest read.  Plain readers stay lock-free: the manifest
        is still replaced atomically, so a read sees a complete old or new
        file.  No-op where ``fcntl`` is unavailable."""
        if fcntl is None:  # pragma: no cover - non-POSIX platform
            yield
            return
        held, waited = _lock_ins()
        t0 = time.perf_counter()
        fd = os.open(os.path.join(self.dir, ".manifest.lock"),
                     os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            waited.inc(time.perf_counter() - t0)
            held.inc()
            try:
                yield
            finally:
                held.dec()
                fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)

    def manifest(self) -> dict:
        try:
            with open(self._manifest_path()) as fh:
                return json.load(fh)
        except (FileNotFoundError, ValueError):
            # no manifest yet (fresh store) / torn JSON can only mean a
            # pre-atomic-rewrite store: start empty, as ever
            return {"version": _FORMAT_VERSION, "models": {}}
        except OSError as e:
            # a manifest that EXISTS but cannot be read (EMFILE, EIO) must
            # not masquerade as an empty store — a publish against the
            # default would re-allocate version 1 over live files
            from ..reliability import resources as _resources

            _resources.note_os_error(e, "modelstore.manifest")
            raise

    def names(self) -> List[str]:
        return sorted(self.manifest()["models"])

    def latest_version(self, name: str) -> Optional[int]:
        v = self.manifest()["models"].get(name)
        return int(v) if v is not None else None

    def active_version(self, name: str) -> Optional[int]:
        """The version unversioned requests should serve: the committed
        ``active`` entry, falling back to latest (stores that never ran a
        lifecycle swap behave exactly as before)."""
        m = self.manifest()
        v = m.get("active", {}).get(name, m["models"].get(name))
        return int(v) if v is not None else None

    def set_active(self, name: str, version: int) -> None:
        """Durably commit ``version`` as the serving version for ``name``
        (atomic manifest rewrite).  This is the hot-swap commit point: a
        kill before this call leaves a store whose restart serves the
        incumbent, whatever has been published since."""
        version = int(version)
        with self._manifest_lock():
            manifest = self.manifest()
            if int(manifest["models"].get(name, 0)) < version:
                raise KeyError(
                    f"cannot activate unpublished version {name!r} "
                    f"v{version}")
            manifest.setdefault("active", {})[name] = version
            self._write_manifest(manifest)

    def serving_entries(self) -> List[Tuple[str, int]]:
        """Every (name, active_version) pair — what a replica loads and
        pins at startup."""
        m = self.manifest()
        active = m.get("active", {})
        return [(n, int(active.get(n, v)))
                for n, v in sorted(m["models"].items())]

    def commit_active(self) -> bool:
        """Explicitly commit every model's RESOLVED serving version (one
        atomic manifest rewrite; a no-op returning False when everything
        is already committed).  A running fleet calls this at start so
        "active" never silently tracks "latest": a later publish moves
        latest, but what serves moves only at its activate commit."""
        with self._manifest_lock():
            manifest = self.manifest()
            active = manifest.setdefault("active", {})
            changed = False
            for name, version in manifest["models"].items():
                if active.get(name) is None:
                    active[name] = int(version)
                    changed = True
            if changed:
                self._write_manifest(manifest)
        return changed

    def _write_manifest(self, manifest: dict) -> None:
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".manifest.tmp")
        try:
            with os.fdopen(fd, "w") as fh:
                json.dump(manifest, fh, indent=1)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self._manifest_path())
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError as e:
                from ..reliability import resources as _resources

                _resources.note_os_error(e, "modelstore.cleanup")
            raise

    # -------------------------------------------------------------- publish
    def publish(self, name: str, source, version: Optional[int] = None,
                ) -> int:
        """Snapshot ``source`` (Booster or .json/.ubj path) into the store.
        Returns the version (auto-incremented when not given).  The whole
        allocate-version → write-files → commit-manifest sequence runs
        under the manifest flock, so concurrent publishers (two lifecycle
        managers over one store) get distinct versions instead of silently
        overwriting each other's files."""
        from .registry import _load_booster
        from .snapshot import InferenceSnapshot

        booster = _load_booster(source)
        snap = InferenceSnapshot.from_booster(booster)
        with self._manifest_lock():
            try:
                return self._publish_locked(name, booster, snap, version)
            except OSError as e:
                # resource failure mid-publish (ENOSPC while writing the
                # arena, EMFILE opening the meta): the tmp files are gone
                # (finally below), the manifest never moved, and the
                # incumbent keeps serving — the lifecycle cycle fails
                # CLEANLY with reason "resource", never a torn arena
                from ..reliability import resources as _resources

                _resources.note_os_error(e, "modelstore.publish")
                _resources.degraded_event(
                    "modelstore", "publish_aborted", model=name,
                    errno=getattr(e, "errno", None))
                raise

    def _publish_locked(self, name: str, booster, snap,
                        version: Optional[int]) -> int:
        """Tmp-file hygiene wrapper: whatever _publish_files leaves behind
        on failure (an arena written but never renamed, a meta mkstemp
        that hit EMFILE) is unlinked, so an aborted publish leaves the
        store directory exactly as it found it."""
        tmps: List[str] = []
        try:
            return self._publish_files(name, booster, snap, version, tmps)
        finally:
            for t in tmps:
                try:
                    os.unlink(t)
                except FileNotFoundError:
                    pass  # committed (renamed away) — the success path
                except OSError as e:
                    from ..reliability import resources as _resources

                    _resources.note_os_error(e, "modelstore.cleanup")

    def _publish_files(self, name: str, booster, snap,
                       version: Optional[int], tmps: List[str]) -> int:
        if version is None:
            version = (self.latest_version(name) or 0) + 1
        version = int(version)

        fields: Dict[str, np.ndarray] = {}
        if snap.stacked is not None:
            for k, v in snap.stacked.items():
                if v is not None:
                    fields["stacked." + k] = np.asarray(v)
        if snap.groups is not None:
            fields["groups"] = np.asarray(snap.groups)
        fields["base_score"] = np.asarray(snap.base_score, np.float32)

        table = {}
        fd, tmp_arena = tempfile.mkstemp(dir=self.dir, suffix=".arena.tmp")
        tmps.append(tmp_arena)
        with os.fdopen(fd, "wb") as fh:
            off = 0
            for key in sorted(fields):
                arr = np.ascontiguousarray(fields[key])
                pad = (-off) % _ALIGN
                fh.write(b"\0" * pad)
                off += pad
                table[key] = {"offset": off, "shape": list(arr.shape),
                              "dtype": arr.dtype.str}
                fh.write(arr.tobytes())
                off += arr.nbytes
            fh.flush()
            os.fsync(fh.fileno())
        # fault seam: a bit flip between checksum computation and the
        # arena hitting disk — verify_checksum must catch it (the
        # lifecycle gate's "checksum" reject; replica attach refuses it)
        from ..reliability import faults as _faults

        spec = _faults.maybe_inject("modelstore.publish")
        if spec is not None and spec.kind == "corrupt":
            import dataclasses as _dc

            # default the flip to byte 0: the arena interleaves fields
            # with alignment padding the checksum does not cover, and a
            # corrupt injection that lands in padding would be a no-op
            if spec.offset is None:
                spec = _dc.replace(spec, offset=0)
            with open(tmp_arena, "rb") as fh:
                damaged = _faults.corrupt_bytes(fh.read(), spec)
            with open(tmp_arena, "wb") as fh:
                fh.write(damaged)
                fh.flush()
                os.fsync(fh.fileno())

        # archive the exact serialized model alongside the inference arena:
        # the lifecycle trainer continues from precisely the bytes being
        # served, not a re-trained approximation of them
        model_blob = bytes(booster.serialize())
        fd, tmp_model = tempfile.mkstemp(dir=self.dir, suffix=".model.tmp")
        tmps.append(tmp_model)
        with os.fdopen(fd, "wb") as fh:
            fh.write(model_blob)
            fh.flush()
            os.fsync(fh.fileno())

        meta = {
            "format": _FORMAT_VERSION,
            "name": name,
            "model_version": version,
            "n_groups": snap.n_groups,
            "depth": snap.depth,
            "n_trees": snap.n_trees,
            "num_features": snap.num_features,
            "feature_names": snap.feature_names,
            "cat_categories": _cat_to_json(snap.cat_categories),
            "objective": str(booster.params.get(
                "objective", "reg:squarederror")),
            "params": _json_params(booster.params),
            "checksum": arena_checksum(fields),
            "fields": table,
        }
        stem = f"{name}.v{version}"
        fd, tmp_meta = tempfile.mkstemp(dir=self.dir, suffix=".meta.tmp")
        tmps.append(tmp_meta)
        with os.fdopen(fd, "w") as fh:
            json.dump(meta, fh, indent=1)
            fh.flush()
            os.fsync(fh.fileno())
        # arena first, then meta, then manifest: a reader resolves through
        # the manifest, so every hop it can see is complete
        os.replace(tmp_arena, os.path.join(self.dir, stem + ".arena"))
        os.replace(tmp_model, os.path.join(self.dir, stem + ".model"))
        os.replace(tmp_meta, os.path.join(self.dir, stem + ".meta.json"))
        manifest = self.manifest()
        manifest["models"][name] = max(
            int(manifest["models"].get(name, 0)), version)
        self._write_manifest(manifest)
        return version

    # -------------------------------------------------------- lifecycle read
    def _stem(self, name: str, version: Optional[int]) -> str:
        if version is None:
            version = self.latest_version(name)
            if version is None:
                raise KeyError(f"model {name!r} is not in the store "
                               f"({self.dir})")
        return f"{name}.v{int(version)}"

    def model_bytes(self, name: str, version: Optional[int] = None) -> bytes:
        """The archived ``Booster.serialize()`` payload for one version —
        the continuation trainer's starting point."""
        path = os.path.join(self.dir, self._stem(name, version) + ".model")
        try:
            with open(path, "rb") as fh:
                return fh.read()
        except FileNotFoundError:
            raise KeyError(
                f"{name!r} v{version} has no archived model bytes (published "
                "before the lifecycle format? re-publish the booster)"
            ) from None

    def booster(self, name: str, version: Optional[int] = None):
        """Rebuild the exact Booster serving as ``(name, version)`` from the
        archived bytes (serialize() round-trip: model + full config)."""
        from ..core import Booster

        bst = Booster()
        bst.unserialize(self.model_bytes(name, version))
        return bst

    def checksum(self, name: str, version: Optional[int] = None,
                 ) -> Optional[str]:
        """The publish-time arena checksum recorded in the meta."""
        stem = self._stem(name, version)
        with open(os.path.join(self.dir, stem + ".meta.json")) as fh:
            return json.load(fh).get("checksum")

    def verify_checksum(self, name: str, version: Optional[int] = None,
                        ) -> bool:
        """Re-derive the arena checksum from the mmapped field views and
        compare it with the publish-time digest — the bitwise half of the
        lifecycle gate.  False = torn/corrupt/drifted arena (or a
        pre-checksum store entry): do not activate."""
        stem = self._stem(name, version)
        meta, view = self._open_arena(stem)
        recorded = meta.get("checksum")
        if recorded is None:
            return False
        ok = arena_checksum({k: view(k) for k in meta["fields"]}
                            ) == recorded
        if not ok:
            from ..reliability import integrity as _integrity

            _integrity.corrupt_detected("arena")
        return ok

    def scrub(self) -> Dict[str, List[Tuple[str, int]]]:
        """Walk EVERY version on disk (not just manifest heads) and
        re-verify each arena against its publish-time checksum — the
        model-store counterpart of the checkpoint-directory scrubber.
        Returns ``{"verified": [(name, version), ...], "corrupt": [...]}``;
        corrupt entries are also counted into
        ``xtb_integrity_corrupt_total{boundary="arena"}`` (by
        :meth:`verify_checksum`) and the scrub pass into
        ``xtb_integrity_scrub_total{target="arena"}``."""
        from ..reliability import integrity as _integrity

        verified: List[Tuple[str, int]] = []
        corrupt: List[Tuple[str, int]] = []
        for fname in sorted(os.listdir(self.dir)):
            if not fname.endswith(".meta.json"):
                continue
            stem = fname[: -len(".meta.json")]
            name, _, vtag = stem.rpartition(".v")
            try:
                version = int(vtag)
            except ValueError:
                continue
            try:
                ok = self.verify_checksum(name, version)
            except (OSError, ValueError, KeyError):
                ok = False  # unreadable meta/arena counts as corrupt
                _integrity.corrupt_detected("arena")
            (verified if ok else corrupt).append((name, version))
        _integrity.scrubbed("arena")
        return {"verified": verified, "corrupt": corrupt}

    # ----------------------------------------------------------------- open
    def _open_arena(self, stem: str):
        """meta dict + a field-view accessor over the mmapped arena — the
        ONE decoder of the arena layout, shared by :meth:`snapshot` and
        :meth:`verify_checksum` so a layout change can never make the
        checksum disagree with what actually serves."""
        with open(os.path.join(self.dir, stem + ".meta.json")) as fh:
            meta = json.load(fh)
        arena = np.memmap(os.path.join(self.dir, stem + ".arena"),
                          dtype=np.uint8, mode="r")

        def view(key):
            ent = meta["fields"].get(key)
            if ent is None:
                return None
            dt = np.dtype(ent["dtype"])
            count = int(np.prod(ent["shape"], dtype=np.int64))
            return np.frombuffer(arena, dtype=dt, count=count,
                                 offset=int(ent["offset"])
                                 ).reshape(ent["shape"])

        return meta, view
    def snapshot(self, name: str, version: Optional[int] = None,
                 device: bool = True):
        """mmap one published model into an :class:`InferenceSnapshot`.

        ``device=True`` runs the arrays through ``jax.device_put`` once
        (zero-copy aliasing on CPU, a single resident copy elsewhere);
        ``device=False`` returns raw memmap views (inspection/tests).
        """
        from .snapshot import InferenceSnapshot

        stem = self._stem(name, version)
        meta, view = self._open_arena(stem)
        if int(meta.get("format", 0)) != _FORMAT_VERSION:
            raise ValueError(
                f"store entry {stem} has format {meta.get('format')!r}; "
                f"this reader understands {_FORMAT_VERSION}")

        def put(arr):
            if arr is None or not device:
                return arr
            import jax

            return jax.device_put(arr)

        stacked = None
        stacked_keys = [k.split(".", 1)[1] for k in meta["fields"]
                        if k.startswith("stacked.")]
        if stacked_keys:
            stacked = {k: put(view("stacked." + k)) for k in stacked_keys}
            if "catm" not in stacked:
                stacked["catm"] = None
        from ..objective import create_objective

        objective = create_objective(meta["objective"], meta["params"])
        snap = InferenceSnapshot(
            stacked=stacked,
            groups=put(view("groups")),
            depth=int(meta["depth"]),
            n_groups=int(meta["n_groups"]),
            base_score=np.asarray(view("base_score"), np.float32),
            objective=objective,
            num_features=int(meta["num_features"]),
            feature_names=meta.get("feature_names"),
            cat_categories=_cat_from_json(meta.get("cat_categories")),
            n_trees=int(meta["n_trees"]),
        )
        snap.store_meta = meta  # program-key inputs ride along (warmcache)
        return snap

    def entries(self) -> List[Tuple[str, int]]:
        """Every (name, latest_version) pair in the manifest."""
        return [(n, int(v)) for n, v in
                sorted(self.manifest()["models"].items())]
