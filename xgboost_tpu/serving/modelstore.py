"""mmap-backed read-only model store: one host copy of every booster.

The fleet-scale residency problem (docs/serving.md "Fleet"): N serving
replicas each building an :class:`InferenceSnapshot` from a model file hold
N private copies of the stacked tree tensors.  The store publishes each
model ONCE as an aligned binary arena + JSON meta; replicas open the arena
with ``np.memmap`` (read-only) and the OS page cache shares the physical
pages across every process on the host — the ``data/extmem.py`` memmap
spill idea applied to model weights instead of training pages.

On the CPU backend the zero-copy goes all the way into XLA:
``jax.device_put`` of a 64-byte-aligned read-only array aliases the mapped
pages instead of copying (PJRT CPU immutable-zero-copy semantics), so M
replicas genuinely hold ONE copy of each booster's arrays in host RAM.  On
accelerator backends the arena is still the single *host* copy; each
device holds its own resident copy as usual.

Layout (``store_dir/``)::

    manifest.json          {"version": 1, "models": {name: latest_version}}
    <name>.v<V>.meta.json  snapshot metadata + arena field table
    <name>.v<V>.arena      64-byte-aligned concatenation of the raw arrays

Publishes are atomic (tmp + rename, manifest rewritten last) so a replica
opening mid-publish sees either the old or the new version, never a torn
one.  The arena stores the *snapshot* tensors (stacked node fields, group
routing, base score) — not the model file — so opening is an mmap + a few
small JSON reads, with no tree parsing on the replica cold path.
"""
from __future__ import annotations

import json
import os
import tempfile
from typing import Dict, List, Optional, Tuple

import numpy as np

_ALIGN = 64  # PJRT CPU zero-copy needs 64-byte-aligned buffers
_FORMAT_VERSION = 1


def _json_params(params: dict) -> dict:
    """The JSON-safe scalar subset of a booster's params — enough to
    rebuild the objective (`create_objective(name, params)` reads scalars
    like num_class / quantile_alpha from it)."""
    out = {}
    for k, v in params.items():
        if isinstance(v, (bool, int, float, str)) or v is None:
            out[k] = v
        elif isinstance(v, (list, tuple)) and all(
                isinstance(x, (bool, int, float, str)) for x in v):
            out[k] = list(v)
    return out


def _cat_to_json(cat_categories) -> Optional[dict]:
    if not cat_categories:
        return None
    out = {}
    for fi, vals in cat_categories.items():
        out[str(int(fi))] = [v.item() if hasattr(v, "item") else v
                             for v in list(vals)]
    return out


def _cat_from_json(obj) -> Optional[dict]:
    if not obj:
        return None
    return {int(k): list(v) for k, v in obj.items()}


class ModelStore:
    """Open (or create) a model store directory.

    Writer side: :meth:`publish` snapshots a Booster (or model path) into
    the arena format.  Reader side: :meth:`snapshot` mmaps a published
    model into an :class:`InferenceSnapshot` whose stacked tensors alias
    the store file.
    """

    def __init__(self, store_dir: str) -> None:
        self.dir = os.fspath(store_dir)
        os.makedirs(self.dir, exist_ok=True)

    # ------------------------------------------------------------- manifest
    def _manifest_path(self) -> str:
        return os.path.join(self.dir, "manifest.json")

    def manifest(self) -> dict:
        try:
            with open(self._manifest_path()) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return {"version": _FORMAT_VERSION, "models": {}}

    def names(self) -> List[str]:
        return sorted(self.manifest()["models"])

    def latest_version(self, name: str) -> Optional[int]:
        v = self.manifest()["models"].get(name)
        return int(v) if v is not None else None

    def _write_manifest(self, manifest: dict) -> None:
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".manifest.tmp")
        with os.fdopen(fd, "w") as fh:
            json.dump(manifest, fh, indent=1)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self._manifest_path())

    # -------------------------------------------------------------- publish
    def publish(self, name: str, source, version: Optional[int] = None,
                ) -> int:
        """Snapshot ``source`` (Booster or .json/.ubj path) into the store.
        Returns the version (auto-incremented when not given)."""
        from .registry import _load_booster
        from .snapshot import InferenceSnapshot

        booster = _load_booster(source)
        snap = InferenceSnapshot.from_booster(booster)
        if version is None:
            version = (self.latest_version(name) or 0) + 1
        version = int(version)

        fields: Dict[str, np.ndarray] = {}
        if snap.stacked is not None:
            for k, v in snap.stacked.items():
                if v is not None:
                    fields["stacked." + k] = np.asarray(v)
        if snap.groups is not None:
            fields["groups"] = np.asarray(snap.groups)
        fields["base_score"] = np.asarray(snap.base_score, np.float32)

        table = {}
        fd, tmp_arena = tempfile.mkstemp(dir=self.dir, suffix=".arena.tmp")
        with os.fdopen(fd, "wb") as fh:
            off = 0
            for key in sorted(fields):
                arr = np.ascontiguousarray(fields[key])
                pad = (-off) % _ALIGN
                fh.write(b"\0" * pad)
                off += pad
                table[key] = {"offset": off, "shape": list(arr.shape),
                              "dtype": arr.dtype.str}
                fh.write(arr.tobytes())
                off += arr.nbytes
            fh.flush()
            os.fsync(fh.fileno())

        meta = {
            "format": _FORMAT_VERSION,
            "name": name,
            "model_version": version,
            "n_groups": snap.n_groups,
            "depth": snap.depth,
            "n_trees": snap.n_trees,
            "num_features": snap.num_features,
            "feature_names": snap.feature_names,
            "cat_categories": _cat_to_json(snap.cat_categories),
            "objective": str(booster.params.get(
                "objective", "reg:squarederror")),
            "params": _json_params(booster.params),
            "fields": table,
        }
        stem = f"{name}.v{version}"
        fd, tmp_meta = tempfile.mkstemp(dir=self.dir, suffix=".meta.tmp")
        with os.fdopen(fd, "w") as fh:
            json.dump(meta, fh, indent=1)
            fh.flush()
            os.fsync(fh.fileno())
        # arena first, then meta, then manifest: a reader resolves through
        # the manifest, so every hop it can see is complete
        os.replace(tmp_arena, os.path.join(self.dir, stem + ".arena"))
        os.replace(tmp_meta, os.path.join(self.dir, stem + ".meta.json"))
        manifest = self.manifest()
        manifest["models"][name] = max(
            int(manifest["models"].get(name, 0)), version)
        self._write_manifest(manifest)
        return version

    # ----------------------------------------------------------------- open
    def snapshot(self, name: str, version: Optional[int] = None,
                 device: bool = True):
        """mmap one published model into an :class:`InferenceSnapshot`.

        ``device=True`` runs the arrays through ``jax.device_put`` once
        (zero-copy aliasing on CPU, a single resident copy elsewhere);
        ``device=False`` returns raw memmap views (inspection/tests).
        """
        from .snapshot import InferenceSnapshot

        if version is None:
            version = self.latest_version(name)
            if version is None:
                raise KeyError(f"model {name!r} is not in the store "
                               f"({self.dir})")
        stem = f"{name}.v{int(version)}"
        with open(os.path.join(self.dir, stem + ".meta.json")) as fh:
            meta = json.load(fh)
        if int(meta.get("format", 0)) != _FORMAT_VERSION:
            raise ValueError(
                f"store entry {stem} has format {meta.get('format')!r}; "
                f"this reader understands {_FORMAT_VERSION}")
        arena = np.memmap(os.path.join(self.dir, stem + ".arena"),
                          dtype=np.uint8, mode="r")

        def view(key):
            ent = meta["fields"].get(key)
            if ent is None:
                return None
            dt = np.dtype(ent["dtype"])
            count = int(np.prod(ent["shape"], dtype=np.int64))
            return np.frombuffer(arena, dtype=dt, count=count,
                                 offset=int(ent["offset"])
                                 ).reshape(ent["shape"])

        def put(arr):
            if arr is None or not device:
                return arr
            import jax

            return jax.device_put(arr)

        stacked = None
        stacked_keys = [k.split(".", 1)[1] for k in meta["fields"]
                        if k.startswith("stacked.")]
        if stacked_keys:
            stacked = {k: put(view("stacked." + k)) for k in stacked_keys}
            if "catm" not in stacked:
                stacked["catm"] = None
        from ..objective import create_objective

        objective = create_objective(meta["objective"], meta["params"])
        snap = InferenceSnapshot(
            stacked=stacked,
            groups=put(view("groups")),
            depth=int(meta["depth"]),
            n_groups=int(meta["n_groups"]),
            base_score=np.asarray(view("base_score"), np.float32),
            objective=objective,
            num_features=int(meta["num_features"]),
            feature_names=meta.get("feature_names"),
            cat_categories=_cat_from_json(meta.get("cat_categories")),
            n_trees=int(meta["n_trees"]),
        )
        snap.store_meta = meta  # program-key inputs ride along (warmcache)
        return snap

    def entries(self) -> List[Tuple[str, int]]:
        """Every (name, latest_version) pair in the manifest."""
        return [(n, int(v)) for n, v in
                sorted(self.manifest()["models"].items())]
