"""Fleet wire protocol: length-prefixed frames with zero-copy payloads.

One frame = ``<u32 header_len><u64 payload_len><u32 crc32><header
JSON><payload>`` (the CRC covers header + payload).
The header is tiny routing metadata (op, request id, model, encoding,
shape); the payload is the row data — and the whole design goal is that
the payload bytes are never copied or decoded at the dispatcher:

- client side: an Arrow RecordBatch is written to an IPC stream (Arrow's
  writer appends the column *buffers* verbatim — no per-value work), a
  numpy batch rides as its raw C-order bytes via ``memoryview``;
- dispatcher: reads the header, forwards the payload memoryview to the
  chosen replica socket untouched (``fleet.dispatch`` routes on header
  fields only);
- replica: ``decode_matrix`` reconstructs the batch *over* the received
  buffer — ``np.frombuffer`` for raw f32, ``pyarrow.ipc`` over a
  ``py_buffer`` view for Arrow (both zero-copy reads; the only copy on
  the whole path is the final columnar->row-major stack at the kernel
  boundary, exactly what the in-process engine pays in ``_as_batch``).

Arrow is optional (pyarrow is an optional dependency repo-wide): the
``arrow`` encoding is negotiated by the client helper and raises cleanly
when pyarrow is absent; ``raw`` always works.

**Integrity** (docs/reliability.md "Integrity & chaos"): every frame's
prefix carries a CRC-32 (``zlib.crc32``, C-speed) over header + payload,
verified by :func:`recv_frame` before the header is even JSON-decoded.  A
mismatch raises :class:`WireCorruptError` — a :class:`WireError` subclass,
so every existing caller already treats it as peer-gone and quarantines
the connection exactly like a ``drop_connection`` fault: the dispatcher
runs its replica-death path (in-flight batch reroutes), the replica exits
its serve loop.  Length prefixes are sanity-bounded (``MAX_HEADER`` /
``MAX_PAYLOAD``) so a garbage prefix can never make the reader allocate
an absurd buffer, and a header that fails to JSON-decode is a
:class:`WireError` too — garbage fails ONE connection, never the fleet.
The ``wire.frame`` fault seam in :func:`send_frame` injects deterministic
byte flips (``corrupt`` kind) after the CRC is computed, which is how the
chaos harness proves the detection end to end.

**Degraded links** (docs/reliability.md "Degraded networks"): the same
``wire.frame`` seam shapes outbound traffic — ``latency`` jitters each
frame from a seeded hash, ``throttle`` paces the write to a byte budget,
``blackhole_tx``/``partition`` silently swallow it (connection open, peer
starving: a half-open link).  The receive side has its own seam,
``wire.recv`` in :func:`recv_frame`, where ``blackhole_rx``/``partition``
consume a full frame without delivering it.  :func:`recv_frame` also
takes a cumulative per-frame ``budget_s`` — the clock starts at the first
prefix byte and covers every subsequent read, so a slow-loris peer
trickling one byte per idle-timeout interval can no longer hold an rx
slot indefinitely (it gets ``budget_s`` total, not per read).
"""
from __future__ import annotations

import ctypes
import json
import os
import socket
import struct
import time
import zlib
from typing import Any, Optional, Tuple

import numpy as np

# <u32 header_len> <u64 payload_len> <u32 crc32(header + payload)>
_PREFIX = struct.Struct("<IQI")

# sanity bounds on the two length prefixes: a corrupted/garbage prefix
# must fail the connection, not OOM the reader with one allocation
MAX_HEADER = 1 << 20          # 1 MiB of routing JSON is already absurd
MAX_PAYLOAD = 1 << 31         # 2 GiB of row data per frame

# payload encodings
RAW = "raw"      # C-order float32 bytes; header carries "shape"
ARROW = "arrow"  # Arrow IPC stream holding one RecordBatch

# replica -> dispatcher telemetry shipment (serving/replica.py
# ship_telemetry): header {"op": TELEMETRY, "label": ...}, payload = JSON
# bytes of telemetry.distributed.snapshot_payload().  Rides the same
# serialized connection as predicts; the dispatcher ingests it without
# touching the in-flight request.  Predict headers additionally carry a
# "trace" id the replica echoes into its span events, which is what lets
# one merged chrome://tracing file pair dispatcher and replica brackets
# per request (docs/observability.md).
TELEMETRY = "telemetry"

# replica -> dispatcher feedback-capture shipment (serving/replica.py, the
# online-learning loop's sample stream): header {"op": FEEDBACK, "model",
# "trace", "shape": [R, F], "oshape": [...]}, payload = the request's raw
# f32 feature rows followed by the raw f32 scores the replica served.
# Unsolicited like TELEMETRY — the dispatcher ingests it without touching
# the in-flight request (docs/online.md "Sampling & the join contract").
FEEDBACK = "feedback"

# dispatcher <-> replica application-level heartbeat (docs/reliability.md
# "Degraded networks"): the dispatcher sends {"op": PING, "seq": n} on a
# schedule; the replica's serve loop answers {"op": PONG, "seq": n}
# immediately.  Because the connection is serialized, a pong queued
# behind a long predict still proves the replica end-to-end alive —
# while a half-open replica (alive process, blackholed return path)
# never answers, which TCP keepalive cannot see.  Pongs feed the
# xtb_net_heartbeat_rtt_seconds histogram and the liveness deadline.
PING = "ping"
PONG = "pong"

# external label producer -> dispatcher (online/feedback.py label feed):
# header {"op": LABEL, "trace": <trace id>}, payload = raw f32 outcome
# values for that trace's rows.  Arrives on a dedicated label-feed
# connection (a hello frame with kind="label_feed" on the fleet's
# listener) and lands in the same bounded symmetric label join as the
# in-process ``label()`` API — same horizon, same counted drops, so a
# remote label pipeline gets no laxer loss accounting than a local one
# (docs/online.md "Sampling & the join contract").
LABEL = "label"


class WireError(RuntimeError):
    """Framing violation on a fleet socket (peer is gone or confused)."""


class WireCorruptError(WireError):
    """Frame CRC mismatch: the bytes on the wire are not the bytes that
    were sent.  Subclasses :class:`WireError` on purpose — corruption is
    handled as peer-gone (quarantine the connection), never by decoding
    the damaged frame."""


# payloads up to this ride in the header's sendall (one segment, one
# syscall).  Two sendalls on a small frame without TCP_NODELAY is the
# classic Nagle + delayed-ACK interaction: the second segment waits for
# the peer's (delayed, up to 40ms) ACK of the first — measured as the
# p99 cliff on the fleet's batch-1 request path.  configure() disables
# Nagle outright; the merge additionally halves small-frame syscalls.
_INLINE_PAYLOAD = 1 << 16


def configure(sock: socket.socket) -> socket.socket:
    """Fleet socket options: TCP_NODELAY (frames are self-contained
    request/response units — buffering them for coalescing only adds
    latency).  Both ends call this on every fleet connection."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError as e:
        # a non-TCP transport (tests pair unix sockets) rejects the
        # option — expected, not a resource event; anything else is
        # classified
        import errno as _errno

        if getattr(e, "errno", None) not in (
                _errno.ENOPROTOOPT, _errno.EOPNOTSUPP, _errno.EINVAL,
                getattr(_errno, "ENOTSUP", _errno.EOPNOTSUPP)):
            from ..reliability import resources as _resources

            _resources.note_os_error(e, "wire.configure")
    return sock


def send_frame(sock: socket.socket, header: dict,
               payload: Optional[Any] = None, *,
               peer: Optional[Any] = None) -> None:
    """Write one frame.  ``payload`` may be bytes/bytearray/memoryview —
    a large one is handed to the kernel as-is (no intermediate concat
    copy of the row data); small ones merge into the prefix+header write
    (one syscall beats one copy at that size).  The prefix CRC covers
    header + payload (~GB/s, a fraction of what the kernel copy costs).
    ``peer`` names the far end (replica label / rank) for link-scoped
    fault matching — a ``partition`` spec cuts only the links whose peer
    hashes onto the wrong side."""
    from ..reliability import faults as _faults

    hdr = json.dumps(header, separators=(",", ":")).encode()
    body = memoryview(payload) if payload is not None else memoryview(b"")
    if body.ndim != 1 or body.itemsize != 1:
        body = body.cast("B")
    crc = zlib.crc32(body, zlib.crc32(hdr))
    prefix = _PREFIX.pack(len(hdr), len(body), crc)
    head = prefix + hdr
    spec = _faults.maybe_inject("wire.frame", rank=peer)
    if spec is not None:
        if spec.kind == "corrupt":
            # deterministic damage AFTER the CRC was computed, scoped to
            # the header+payload region the CRC covers: the receiver must
            # detect it (WireCorruptError) and quarantine the connection.
            # (A flip in the length prefix itself is indistinguishable
            # from a stalled or insane peer — the MAX_* bounds and
            # callers' timeouts own that case.)
            sock.sendall(prefix
                         + _faults.corrupt_bytes(hdr + bytes(body), spec))
            return
        if spec.kind == "blackhole_tx" or (
                spec.kind == "partition"
                and _faults.partition_blocks(spec, peer)):
            # half-open link, outbound side: the bytes vanish but the
            # connection stays up — the peer sees silence, never EOF.
            # Detection is the application's job (heartbeat deadline,
            # per-link budget), which is the point.
            return
        if spec.kind == "throttle":
            time.sleep(_faults.throttle_seconds(
                spec, len(head) + len(body)))
    if len(body) and len(body) <= _INLINE_PAYLOAD:
        sock.sendall(head + bytes(body))
        return
    sock.sendall(head)
    if len(body):
        sock.sendall(body)


_NATIVE = None  # None = unresolved; False = disabled/unavailable; CDLL = ready


def _native_lib():
    """The native rx library (native/xtb_wire.cc via utils/native), or
    None for the pure-Python frame path.  ``XGBOOST_TPU_WIRE_NATIVE=0``
    is the kill switch (default on when the library loads); resolved
    once per process."""
    global _NATIVE
    if _NATIVE is None:
        if os.environ.get("XGBOOST_TPU_WIRE_NATIVE", "1").strip().lower() \
                in ("", "0", "false", "off", "no"):
            _NATIVE = False
        else:
            from ..utils.native import load_wire

            _NATIVE = load_wire() or False
    return _NATIVE or None


class _NativeReader:
    """Frame source backed by libxtb_wire: :func:`recv_frame` reads the
    whole frame — prefix, header, payload, CRC verify — in two native
    calls (one GIL release each) instead of per-chunk interpreter reads.
    Under a sharded dispatcher the GIL *reacquire* per read is the
    convoy cost this removes; the thread takes the GIL back only to
    JSON-decode the tiny header.  Only created for sockets in plain
    blocking mode at a frame boundary; the socket stays owned by the
    caller."""
    __slots__ = ("sock", "fd")

    def __init__(self, sock: socket.socket):
        self.sock = sock  # keeps the fd alive for the reader's lifetime
        self.fd = sock.fileno()

    def close(self) -> None:
        self.sock = None


def reader(sock: socket.socket):
    """Buffered frame source for a long-lived fleet connection.  A frame
    is 3+ reads (prefix, header, payload); on a raw socket each is a
    syscall AND a GIL release/reacquire — and under a many-threaded
    dispatcher the reacquire, not the syscall, is the cost (profiled at
    ~ms under convoy).  A ``BufferedReader`` usually serves the prefix
    and header out of the buffer: one GIL event per frame instead of
    three.  Safe to create any time the stream is at a frame boundary
    (``makefile`` shares the fd — no dup, no double-buffering).

    When the native wire library is available (utils/native.load_wire;
    ``XGBOOST_TPU_WIRE_NATIVE=0`` forces it off) and the socket is in
    plain blocking mode, the source is a :class:`_NativeReader` instead:
    one GIL release covers the whole frame read and the CRC verify,
    under the identical frame contract (bounds, cumulative slow-loris
    budget, CRC semantics, fault seams stay Python-side)."""
    if _native_lib() is not None and sock.gettimeout() is None:
        return _NativeReader(sock)
    return sock.makefile("rb", buffering=1 << 16)


def _recv_exact(stream, n: int,
                deadline: Optional[float] = None) -> memoryview:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    readinto = getattr(stream, "readinto", None)
    while got < n:
        r = (readinto(view[got:]) if readinto is not None
             else stream.recv_into(view[got:], n - got))
        if not r:
            raise WireError("connection closed mid-frame")
        got += r
        # the slow-loris bound: every partial read is a checkpoint
        # against the frame's CUMULATIVE deadline, so a peer drip-feeding
        # one byte per idle-timeout interval exhausts one budget instead
        # of resetting it on each byte
        if deadline is not None and got < n \
                and time.monotonic() >= deadline:
            raise WireError(
                f"frame read exceeded its cumulative deadline with "
                f"{n - got} of {n} bytes outstanding (slow-loris bound)")
    return memoryview(buf)


def recv_frame(stream, *, budget_s: Optional[float] = None,
               peer: Optional[Any] = None) -> Tuple[dict, memoryview]:
    """Read one frame -> (header, payload view) from a socket or a
    :func:`reader` stream.  Raises WireError on EOF at a frame boundary
    too (callers treat any WireError as peer-gone); length-prefix
    violations and CRC mismatches (:class:`WireCorruptError`) are
    WireErrors as well, so a poisoned connection fails itself, not the
    fleet, and damaged bytes are never JSON-decoded.

    ``budget_s`` bounds one frame's total read wall: the clock starts
    when the first prefix byte arrives (idle time between frames is
    free) and a frame still incomplete at the deadline is a WireError —
    the slow-loris bound.  It needs at least a trickle to check against
    (each arriving chunk is a checkpoint); a peer sending *nothing* is
    the idle-timeout/heartbeat layer's case, not this one.  ``peer``
    scopes rx-side fault matching (``wire.recv`` seam), where
    ``blackhole_rx``/``partition`` consume a frame without delivering
    it — the half-open link's inbound side."""
    from ..reliability import faults as _faults

    if isinstance(stream, _NativeReader):
        return _recv_frame_native(stream, budget_s=budget_s, peer=peer)
    while True:
        spec = _faults.maybe_inject("wire.recv", rank=peer)
        first = _recv_exact(stream, 1)
        deadline = (time.monotonic() + budget_s) if budget_s is not None \
            else None
        rest = _recv_exact(stream, _PREFIX.size - 1, deadline)
        hlen, plen, crc = _PREFIX.unpack(bytes(first) + bytes(rest))
        if hlen > MAX_HEADER:
            raise WireError(f"unreasonable header length {hlen}")
        if plen > MAX_PAYLOAD:
            raise WireError(f"unreasonable payload length {plen}")
        hdr_bytes = _recv_exact(stream, hlen, deadline)
        payload = _recv_exact(stream, plen, deadline) if plen \
            else memoryview(b"")
        if zlib.crc32(payload, zlib.crc32(hdr_bytes)) != crc:
            from ..reliability import integrity as _integrity

            _integrity.corrupt_detected("wire")
            raise WireCorruptError(
                f"frame CRC mismatch ({hlen}B header, {plen}B payload): "
                "corrupted in transit — quarantining the connection")
        if spec is not None and (
                spec.kind == "blackhole_rx"
                or (spec.kind == "partition"
                    and _faults.partition_blocks(spec, peer))):
            # half-open link, inbound side: the kernel delivered the
            # frame, the application never sees it.  Loop for the next
            # frame — the connection stays alive and silent.
            continue
        try:
            header = json.loads(bytes(hdr_bytes))
        except ValueError as e:
            raise WireError(f"undecodable frame header: {e}") from e
        if not isinstance(header, dict):
            raise WireError(f"frame header is {type(header).__name__}, "
                            "expected a JSON object")
        return header, payload


def _native_raise(rc: int, what: str) -> None:
    """Map a libxtb_wire return code onto the same WireError taxonomy the
    Python reader raises (CRC handled at the call site — it also bumps
    the integrity counter)."""
    if rc in (1, -1):
        raise WireError("connection closed mid-frame")
    if rc == -2:
        raise WireError(
            f"frame {what} read exceeded its cumulative deadline "
            "(slow-loris bound)")
    raise WireError(f"socket read failed during frame {what} (rc={rc})")


def _recv_frame_native(rd: "_NativeReader", *,
                       budget_s: Optional[float] = None,
                       peer: Optional[Any] = None) -> Tuple[dict, memoryview]:
    """:func:`recv_frame` over a :class:`_NativeReader`: the byte loop
    (prefix read, body read, CRC) runs in libxtb_wire under ONE GIL
    release per call; every policy decision — length bounds, the
    ``wire.recv`` fault seam with its blackhole re-loop, corruption
    accounting, error classification — stays here so both paths are
    observably identical."""
    from ..reliability import faults as _faults

    lib = _native_lib()
    while True:
        spec = _faults.maybe_inject("wire.recv", rank=peer)
        hlen = ctypes.c_uint()
        plen = ctypes.c_ulonglong()
        crc = ctypes.c_uint()
        deadline = ctypes.c_double()
        rc = lib.xtb_wire_read_prefix(
            rd.fd, float(budget_s) if budget_s is not None else 0.0,
            ctypes.byref(hlen), ctypes.byref(plen), ctypes.byref(crc),
            ctypes.byref(deadline))
        if rc != 0:
            _native_raise(rc, "prefix")
        hl, pl = int(hlen.value), int(plen.value)
        if hl > MAX_HEADER:
            raise WireError(f"unreasonable header length {hl}")
        if pl > MAX_PAYLOAD:
            raise WireError(f"unreasonable payload length {pl}")
        buf = bytearray(hl + pl)
        rc = lib.xtb_wire_read_body(
            rd.fd, (ctypes.c_ubyte * len(buf)).from_buffer(buf), len(buf),
            deadline.value, crc.value)
        if rc == -6:
            from ..reliability import integrity as _integrity

            _integrity.corrupt_detected("wire")
            raise WireCorruptError(
                f"frame CRC mismatch ({hl}B header, {pl}B payload): "
                "corrupted in transit — quarantining the connection")
        if rc != 0:
            _native_raise(rc, "body")
        if spec is not None and (
                spec.kind == "blackhole_rx"
                or (spec.kind == "partition"
                    and _faults.partition_blocks(spec, peer))):
            # half-open link, inbound side — same contract as the Python
            # reader: the frame was consumed, the application never sees
            # it, the connection stays alive and silent
            continue
        view = memoryview(buf)
        try:
            header = json.loads(bytes(view[:hl]))
        except ValueError as e:
            raise WireError(f"undecodable frame header: {e}") from e
        if not isinstance(header, dict):
            raise WireError(f"frame header is {type(header).__name__}, "
                            "expected a JSON object")
        return header, view[hl:]


# ---------------------------------------------------------------- encoding
def encode_raw(X: np.ndarray) -> Tuple[dict, memoryview]:
    """(header fields, payload) for a numpy batch — zero-copy when ``X``
    is already C-contiguous float32."""
    X = np.ascontiguousarray(X, np.float32)
    if X.ndim == 1:
        X = X[None, :]
    return ({"enc": RAW, "shape": list(X.shape)},
            memoryview(X).cast("B"))


def encode_arrow(batch) -> Tuple[dict, memoryview]:
    """(header fields, payload) for a pyarrow RecordBatch/Table: one IPC
    stream, column buffers appended without per-value work."""
    import pyarrow as pa

    if isinstance(batch, pa.Table):
        batch = batch.combine_chunks().to_batches()[0] if batch.num_rows \
            else pa.RecordBatch.from_pydict(
                {n: [] for n in batch.schema.names}, schema=batch.schema)
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, batch.schema) as writer:
        writer.write_batch(batch)
    buf = sink.getvalue()
    return ({"enc": ARROW, "shape": [batch.num_rows, batch.num_columns]},
            memoryview(buf))


def label_feed(host: str, port: int, label: str = "labeler",
               timeout: Optional[float] = 30.0) -> socket.socket:
    """Open a label-feed channel to a fleet listener
    (``ServingFleet.label_endpoint()``): connect, configure, and send
    the ``kind="label_feed"`` hello that routes this connection to the
    fleet's label rx loop instead of replica bookkeeping.  ``timeout``
    bounds the connect AND every later send on the socket — a
    black-holed route is a detected fault, not a wedged producer.  The
    caller owns the socket (close it when the producer is done)."""
    sock = configure(socket.create_connection((host, int(port)),
                                              timeout=timeout))
    send_frame(sock, {"op": "hello", "kind": "label_feed",
                      "label": label})
    return sock


def send_label(sock: socket.socket, trace: str, y, *,
               peer: Optional[Any] = None) -> None:
    """One ``op="label"`` frame on a label-feed channel: the outcome
    values for ``trace``'s rows, float32 raw — joined driver-side by the
    online loop's FeedbackHub (docs/online.md)."""
    arr = np.ascontiguousarray(np.asarray(y, np.float32).reshape(-1))
    send_frame(sock, {"op": LABEL, "trace": trace,
                      "shape": [int(arr.shape[0])]},
               memoryview(arr).cast("B"), peer=peer)


def decode_matrix(header: dict, payload) -> np.ndarray:
    """Reconstruct the (R, F) float32 batch over the received buffer.

    ``raw``: a zero-copy ``np.frombuffer`` view.  ``arrow``: zero-copy IPC
    read; float32 null-free columns are stacked straight off the Arrow
    buffers, anything else (other dtypes, nulls, dictionary categoricals)
    goes through the same semantics as ``data/arrow.py`` ingestion."""
    enc = header.get("enc", RAW)
    if enc == RAW:
        R, F = (int(x) for x in header["shape"])
        return np.frombuffer(payload, np.float32).reshape(R, F)
    if enc == ARROW:
        from ..data.arrow import ipc_batch_to_dense
        return ipc_batch_to_dense(payload)
    raise WireError(f"unknown payload encoding {enc!r}")
