"""Fleet wire protocol: length-prefixed frames with zero-copy payloads.

One frame = ``<u32 header_len><u64 payload_len><u32 crc32><header
JSON><payload>`` (the CRC covers header + payload).
The header is tiny routing metadata (op, request id, model, encoding,
shape); the payload is the row data — and the whole design goal is that
the payload bytes are never copied or decoded at the dispatcher:

- client side: an Arrow RecordBatch is written to an IPC stream (Arrow's
  writer appends the column *buffers* verbatim — no per-value work), a
  numpy batch rides as its raw C-order bytes via ``memoryview``;
- dispatcher: reads the header, forwards the payload memoryview to the
  chosen replica socket untouched (``fleet.dispatch`` routes on header
  fields only);
- replica: ``decode_matrix`` reconstructs the batch *over* the received
  buffer — ``np.frombuffer`` for raw f32, ``pyarrow.ipc`` over a
  ``py_buffer`` view for Arrow (both zero-copy reads; the only copy on
  the whole path is the final columnar->row-major stack at the kernel
  boundary, exactly what the in-process engine pays in ``_as_batch``).

Arrow is optional (pyarrow is an optional dependency repo-wide): the
``arrow`` encoding is negotiated by the client helper and raises cleanly
when pyarrow is absent; ``raw`` always works.

**Integrity** (docs/reliability.md "Integrity & chaos"): every frame's
prefix carries a CRC-32 (``zlib.crc32``, C-speed) over header + payload,
verified by :func:`recv_frame` before the header is even JSON-decoded.  A
mismatch raises :class:`WireCorruptError` — a :class:`WireError` subclass,
so every existing caller already treats it as peer-gone and quarantines
the connection exactly like a ``drop_connection`` fault: the dispatcher
runs its replica-death path (in-flight batch reroutes), the replica exits
its serve loop.  Length prefixes are sanity-bounded (``MAX_HEADER`` /
``MAX_PAYLOAD``) so a garbage prefix can never make the reader allocate
an absurd buffer, and a header that fails to JSON-decode is a
:class:`WireError` too — garbage fails ONE connection, never the fleet.
The ``wire.frame`` fault seam in :func:`send_frame` injects deterministic
byte flips (``corrupt`` kind) after the CRC is computed, which is how the
chaos harness proves the detection end to end.
"""
from __future__ import annotations

import json
import socket
import struct
import zlib
from typing import Any, Optional, Tuple

import numpy as np

# <u32 header_len> <u64 payload_len> <u32 crc32(header + payload)>
_PREFIX = struct.Struct("<IQI")

# sanity bounds on the two length prefixes: a corrupted/garbage prefix
# must fail the connection, not OOM the reader with one allocation
MAX_HEADER = 1 << 20          # 1 MiB of routing JSON is already absurd
MAX_PAYLOAD = 1 << 31         # 2 GiB of row data per frame

# payload encodings
RAW = "raw"      # C-order float32 bytes; header carries "shape"
ARROW = "arrow"  # Arrow IPC stream holding one RecordBatch

# replica -> dispatcher telemetry shipment (serving/replica.py
# ship_telemetry): header {"op": TELEMETRY, "label": ...}, payload = JSON
# bytes of telemetry.distributed.snapshot_payload().  Rides the same
# serialized connection as predicts; the dispatcher ingests it without
# touching the in-flight request.  Predict headers additionally carry a
# "trace" id the replica echoes into its span events, which is what lets
# one merged chrome://tracing file pair dispatcher and replica brackets
# per request (docs/observability.md).
TELEMETRY = "telemetry"

# replica -> dispatcher feedback-capture shipment (serving/replica.py, the
# online-learning loop's sample stream): header {"op": FEEDBACK, "model",
# "trace", "shape": [R, F], "oshape": [...]}, payload = the request's raw
# f32 feature rows followed by the raw f32 scores the replica served.
# Unsolicited like TELEMETRY — the dispatcher ingests it without touching
# the in-flight request (docs/online.md "Sampling & the join contract").
FEEDBACK = "feedback"


class WireError(RuntimeError):
    """Framing violation on a fleet socket (peer is gone or confused)."""


class WireCorruptError(WireError):
    """Frame CRC mismatch: the bytes on the wire are not the bytes that
    were sent.  Subclasses :class:`WireError` on purpose — corruption is
    handled as peer-gone (quarantine the connection), never by decoding
    the damaged frame."""


# payloads up to this ride in the header's sendall (one segment, one
# syscall).  Two sendalls on a small frame without TCP_NODELAY is the
# classic Nagle + delayed-ACK interaction: the second segment waits for
# the peer's (delayed, up to 40ms) ACK of the first — measured as the
# p99 cliff on the fleet's batch-1 request path.  configure() disables
# Nagle outright; the merge additionally halves small-frame syscalls.
_INLINE_PAYLOAD = 1 << 16


def configure(sock: socket.socket) -> socket.socket:
    """Fleet socket options: TCP_NODELAY (frames are self-contained
    request/response units — buffering them for coalescing only adds
    latency).  Both ends call this on every fleet connection."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError as e:
        # a non-TCP transport (tests pair unix sockets) rejects the
        # option — expected, not a resource event; anything else is
        # classified
        import errno as _errno

        if getattr(e, "errno", None) not in (
                _errno.ENOPROTOOPT, _errno.EOPNOTSUPP, _errno.EINVAL,
                getattr(_errno, "ENOTSUP", _errno.EOPNOTSUPP)):
            from ..reliability import resources as _resources

            _resources.note_os_error(e, "wire.configure")
    return sock


def send_frame(sock: socket.socket, header: dict,
               payload: Optional[Any] = None) -> None:
    """Write one frame.  ``payload`` may be bytes/bytearray/memoryview —
    a large one is handed to the kernel as-is (no intermediate concat
    copy of the row data); small ones merge into the prefix+header write
    (one syscall beats one copy at that size).  The prefix CRC covers
    header + payload (~GB/s, a fraction of what the kernel copy costs)."""
    from ..reliability import faults as _faults

    hdr = json.dumps(header, separators=(",", ":")).encode()
    body = memoryview(payload) if payload is not None else memoryview(b"")
    if body.ndim != 1 or body.itemsize != 1:
        body = body.cast("B")
    crc = zlib.crc32(body, zlib.crc32(hdr))
    prefix = _PREFIX.pack(len(hdr), len(body), crc)
    head = prefix + hdr
    spec = _faults.maybe_inject("wire.frame")
    if spec is not None and spec.kind == "corrupt":
        # deterministic damage AFTER the CRC was computed, scoped to the
        # header+payload region the CRC covers: the receiver must detect
        # it (WireCorruptError) and quarantine the connection.  (A flip
        # in the length prefix itself is indistinguishable from a stalled
        # or insane peer — the MAX_* bounds and callers' timeouts own
        # that case.)
        sock.sendall(prefix
                     + _faults.corrupt_bytes(hdr + bytes(body), spec))
        return
    if len(body) and len(body) <= _INLINE_PAYLOAD:
        sock.sendall(head + bytes(body))
        return
    sock.sendall(head)
    if len(body):
        sock.sendall(body)


def reader(sock: socket.socket):
    """Buffered frame source for a long-lived fleet connection.  A frame
    is 3+ reads (prefix, header, payload); on a raw socket each is a
    syscall AND a GIL release/reacquire — and under a many-threaded
    dispatcher the reacquire, not the syscall, is the cost (profiled at
    ~ms under convoy).  A ``BufferedReader`` usually serves the prefix
    and header out of the buffer: one GIL event per frame instead of
    three.  Safe to create any time the stream is at a frame boundary
    (``makefile`` shares the fd — no dup, no double-buffering)."""
    return sock.makefile("rb", buffering=1 << 16)


def _recv_exact(stream, n: int) -> memoryview:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    readinto = getattr(stream, "readinto", None)
    while got < n:
        r = (readinto(view[got:]) if readinto is not None
             else stream.recv_into(view[got:], n - got))
        if not r:
            raise WireError("connection closed mid-frame")
        got += r
    return memoryview(buf)


def recv_frame(stream) -> Tuple[dict, memoryview]:
    """Read one frame -> (header, payload view) from a socket or a
    :func:`reader` stream.  Raises WireError on EOF at a frame boundary
    too (callers treat any WireError as peer-gone); length-prefix
    violations and CRC mismatches (:class:`WireCorruptError`) are
    WireErrors as well, so a poisoned connection fails itself, not the
    fleet, and damaged bytes are never JSON-decoded."""
    prefix = _recv_exact(stream, _PREFIX.size)
    hlen, plen, crc = _PREFIX.unpack(prefix)
    if hlen > MAX_HEADER:
        raise WireError(f"unreasonable header length {hlen}")
    if plen > MAX_PAYLOAD:
        raise WireError(f"unreasonable payload length {plen}")
    hdr_bytes = _recv_exact(stream, hlen)
    payload = _recv_exact(stream, plen) if plen else memoryview(b"")
    if zlib.crc32(payload, zlib.crc32(hdr_bytes)) != crc:
        from ..reliability import integrity as _integrity

        _integrity.corrupt_detected("wire")
        raise WireCorruptError(
            f"frame CRC mismatch ({hlen}B header, {plen}B payload): "
            "corrupted in transit — quarantining the connection")
    try:
        header = json.loads(bytes(hdr_bytes))
    except ValueError as e:
        raise WireError(f"undecodable frame header: {e}") from e
    if not isinstance(header, dict):
        raise WireError(f"frame header is {type(header).__name__}, "
                        "expected a JSON object")
    return header, payload


# ---------------------------------------------------------------- encoding
def encode_raw(X: np.ndarray) -> Tuple[dict, memoryview]:
    """(header fields, payload) for a numpy batch — zero-copy when ``X``
    is already C-contiguous float32."""
    X = np.ascontiguousarray(X, np.float32)
    if X.ndim == 1:
        X = X[None, :]
    return ({"enc": RAW, "shape": list(X.shape)},
            memoryview(X).cast("B"))


def encode_arrow(batch) -> Tuple[dict, memoryview]:
    """(header fields, payload) for a pyarrow RecordBatch/Table: one IPC
    stream, column buffers appended without per-value work."""
    import pyarrow as pa

    if isinstance(batch, pa.Table):
        batch = batch.combine_chunks().to_batches()[0] if batch.num_rows \
            else pa.RecordBatch.from_pydict(
                {n: [] for n in batch.schema.names}, schema=batch.schema)
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, batch.schema) as writer:
        writer.write_batch(batch)
    buf = sink.getvalue()
    return ({"enc": ARROW, "shape": [batch.num_rows, batch.num_columns]},
            memoryview(buf))


def decode_matrix(header: dict, payload) -> np.ndarray:
    """Reconstruct the (R, F) float32 batch over the received buffer.

    ``raw``: a zero-copy ``np.frombuffer`` view.  ``arrow``: zero-copy IPC
    read; float32 null-free columns are stacked straight off the Arrow
    buffers, anything else (other dtypes, nulls, dictionary categoricals)
    goes through the same semantics as ``data/arrow.py`` ingestion."""
    enc = header.get("enc", RAW)
    if enc == RAW:
        R, F = (int(x) for x in header["shape"])
        return np.frombuffer(payload, np.float32).reshape(R, F)
    if enc == ARROW:
        from ..data.arrow import ipc_batch_to_dense
        return ipc_batch_to_dense(payload)
    raise WireError(f"unknown payload encoding {enc!r}")
