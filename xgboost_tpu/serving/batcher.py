"""Dynamic micro-batcher: coalesce concurrent predict requests per model.

Admission policy (the standard dynamic-batching contract, cf. arXiv:1806.11248
§6 where prediction throughput comes from batching rows, not threads): a
request joins the queue of its (model, options) key and the worker launches a
batch as soon as EITHER the queued rows reach ``max_batch`` OR the oldest
request has waited ``max_delay_us``.  Batches concatenate request rows in FIFO
order, run once through the engine's compiled bucket program, and the result
is split back per caller — so N concurrent callers cost one traced program
execution instead of N, and tail latency is bounded by the admission delay
plus one batch execution.

A single worker thread executes all batches.  That is deliberate: the JAX/C
ABI dispatch path serializes on the interpreter anyway (docs/serving.md), so
extra executor threads would only add context switches; ordering through one
worker also keeps results deterministic.

Failure contract (docs/reliability.md): the worker thread dying must never
wedge callers.  ``submit()`` probes worker liveness and raises
:class:`WorkerDiedError` (chained to the original worker exception) instead
of returning a future nobody will resolve; a worker that dies with requests
queued fails every pending future on its way down.  ``max_queue_rows``
bounds the queue — beyond it ``submit()`` sheds with :class:`QueueFullError`
(counted in ``xtb_serve_shed_total``) so an overloaded engine degrades by
rejecting fast, not by growing an unbounded backlog.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Deque, Dict, List, Optional

import numpy as np

from ..reliability import faults as _faults
from ..telemetry import spans as _spans


class WorkerDiedError(RuntimeError):
    """The batcher worker thread is not running; ``__cause__`` carries the
    exception that killed it (when one escaped)."""


class QueueFullError(RuntimeError):
    """Request shed: admitting it would exceed ``max_queue_rows``."""


def _key_label(key: Any) -> str:
    return key[0] if isinstance(key, tuple) else str(key)


class _Request:
    __slots__ = ("X", "future", "t_enqueue_ns", "ctx")

    def __init__(self, X: np.ndarray, ctx: Any) -> None:
        self.X = X
        self.future: Future = Future()
        self.t_enqueue_ns = time.perf_counter_ns()
        self.ctx = ctx


class MicroBatcher:
    """``submit(key, X, ctx)`` -> Future of the per-request result rows.

    ``execute(key, X, ctx)`` is the engine callback running one coalesced
    batch; ``ctx`` is an opaque per-key context (resolved model snapshot +
    options) taken from the first request of the batch.
    """

    def __init__(self, execute: Callable[[Any, np.ndarray, Any], np.ndarray],
                 *, max_batch: int = 4096, max_delay_us: int = 2000,
                 max_queue_rows: Optional[int] = None, metrics=None) -> None:
        self._execute = execute
        self.max_batch = int(max_batch)
        self.max_delay_ns = int(max_delay_us) * 1000
        self.max_queue_rows = (int(max_queue_rows)
                               if max_queue_rows is not None else None)
        self._metrics = metrics
        self._queues: Dict[Any, Deque[_Request]] = {}
        self._rows: Dict[Any, int] = {}  # running per-key queued-row counts
        self._total_rows = 0             # across all keys (shed bound)
        self._cv = threading.Condition()
        self._closed = False
        self._worker_exc: Optional[BaseException] = None
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="xtb-serve-batcher")
        self._worker.start()

    # ------------------------------------------------------------------ API
    def worker_alive(self) -> bool:
        return self._worker.is_alive() and self._worker_exc is None

    def submit(self, key: Any, X: np.ndarray, ctx: Any = None) -> Future:
        req = _Request(X, ctx)
        with self._cv:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            if not self.worker_alive():
                # fail fast with the REAL cause — returning a future no
                # worker will ever resolve blocks the caller forever
                raise WorkerDiedError(
                    "micro-batcher worker thread is not running"
                ) from self._worker_exc
            if (self.max_queue_rows is not None
                    and self._total_rows + len(X) > self.max_queue_rows
                    and self._total_rows > 0):
                # shed under overload (a single oversized request with an
                # empty queue is still admitted — it must be servable)
                if self._metrics is not None:
                    self._metrics.observe_shed(_key_label(key))
                raise QueueFullError(
                    f"queue full: {self._total_rows} rows waiting "
                    f"(max_queue_rows={self.max_queue_rows})")
            self._queues.setdefault(key, deque()).append(req)
            self._rows[key] = self._rows.get(key, 0) + len(X)
            self._total_rows += len(X)
            if self._metrics is not None:
                self._metrics.queue_delta(len(X))
            self._cv.notify()
        return req.future

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify()
        self._worker.join()

    # ---------------------------------------------------------------- worker
    def _drain(self, key: Any) -> List[_Request]:
        """Pop FIFO requests up to max_batch rows (always at least one live
        request, so an oversized single request still runs as its own
        batch).  Requests whose future was cancelled — a caller that gave
        up at its deadline — are discarded without consuming batch budget:
        executing them would burn device time producing results nobody
        reads, falling further behind and timing out MORE callers (may
        return an empty batch when everything queued was abandoned)."""
        q = self._queues[key]
        batch, popped, batch_rows = [], 0, 0
        while q:
            if q[0].future.cancelled():
                popped += len(q.popleft().X)
                continue
            if batch and batch_rows + len(q[0].X) > self.max_batch:
                break
            r = q.popleft()
            batch.append(r)
            popped += len(r.X)
            batch_rows += len(r.X)
        if q:
            self._rows[key] -= popped
        else:
            del self._queues[key]
            del self._rows[key]
        self._total_rows -= popped
        if self._metrics is not None:
            self._metrics.queue_delta(-popped)
        return batch

    def _run_batch(self, key: Any, batch: List[_Request]) -> None:
        # the whole prepare/execute/account sequence is guarded: an escaped
        # exception would kill the sole worker thread and leave every pending
        # and future submit() hanging on a future nobody will ever resolve
        try:
            X = (batch[0].X if len(batch) == 1
                 else np.concatenate([r.X for r in batch], axis=0))
            t0 = time.perf_counter_ns()
            if _spans.enabled():
                # admission wait of the batch head: enqueue -> launch (the
                # other latency component besides serve.execute); recorded
                # with the TRUE start timestamp so trace spans line up
                _spans.record_phase("serve.batch_wait",
                                    batch[0].t_enqueue_ns,
                                    t0 - batch[0].t_enqueue_ns)
            out = self._execute(key, X, batch[0].ctx)
            exec_ns = time.perf_counter_ns() - t0
            if self._metrics is not None:
                self._metrics.observe_batch(_key_label(key), len(X),
                                            len(batch), exec_ns)
        except BaseException as e:  # fan the failure out to every caller
            for r in batch:
                if not r.future.set_running_or_notify_cancel():
                    continue
                r.future.set_exception(e)
            return
        off = 0
        for r in batch:
            n = len(r.X)
            if r.future.set_running_or_notify_cancel():
                r.future.set_result(out[off:off + n])
            off += n

    def _loop(self) -> None:
        try:
            self._loop_impl()
        except BaseException as e:
            self._on_worker_death(e)

    def _on_worker_death(self, exc: BaseException) -> None:
        """The sole worker is gone: record why, fail every queued request
        (their futures would otherwise never resolve), wake everyone."""
        with self._cv:
            self._worker_exc = exc
            pending = [r for q in self._queues.values() for r in q]
            drained = self._total_rows
            self._queues.clear()
            self._rows.clear()
            self._total_rows = 0
            if self._metrics is not None and drained:
                self._metrics.queue_delta(-drained)
            self._cv.notify_all()
        err = WorkerDiedError("micro-batcher worker died with requests "
                              "queued")
        err.__cause__ = exc
        for r in pending:
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(err)

    def _loop_impl(self) -> None:
        while True:
            # seam: 'exception' here IS a worker-thread death — the
            # deterministic stand-in for a bug escaping _loop_impl
            _faults.maybe_inject("serve.worker")
            with self._cv:
                while True:
                    # scan EVERY key: a queue that reached max_batch launches
                    # now even while another key's delay window is still open
                    # (oldest-key-only evaluation would park a full batch
                    # behind a lone slow-filling key for the whole delay);
                    # among ready keys the oldest head keeps FIFO fairness
                    now = time.perf_counter_ns()
                    key, key_t, earliest = None, None, None
                    for k, q in self._queues.items():
                        if not q:
                            continue
                        head_t = q[0].t_enqueue_ns
                        deadline = head_t + self.max_delay_ns
                        rows = self._rows[k]
                        if (rows >= self.max_batch or deadline <= now
                                or self._closed):
                            if key_t is None or head_t < key_t:
                                key, key_t = k, head_t
                        elif earliest is None or deadline < earliest:
                            earliest = deadline
                    if key is not None:
                        batch = self._drain(key)
                        if not batch:  # all abandoned: rescan, don't execute
                            continue
                        break
                    if earliest is None:  # nothing queued at all
                        if self._closed:
                            return
                        # bounded idle wait (XTB701): submit()/close()
                        # notify immediately; the periodic wake only
                        # re-checks _closed so a lost notification can
                        # never wedge the worker forever
                        self._cv.wait(timeout=1.0)
                    else:
                        self._cv.wait(timeout=(earliest - now) / 1e9)
            self._run_batch(key, batch)
