"""Frozen inference snapshot of a trained Booster.

The serving analogue of the reference's thread-safe Learner handle
(src/c_api/c_api.cc keeps one Learner per BoosterHandle and predicts from
many threads): everything prediction needs — the stacked padded tree
tensors, group routing, base score, and the objective transform — is copied
OUT of the live Booster into immutable device-resident arrays, so serving
never races training-side mutation (continued training, attribute writes)
and never touches a DMatrix cache.  The stacked layout is the cache-conscious
structure-of-arrays form of arXiv:1603.02754 §4 applied to inference: one
(T, M) tensor per node field, resident in device memory for the model's
lifetime in the registry.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..ops.predict import (bucket_rows, pad_margin, pad_rows,
                           run_stacked_margin)


class InferenceSnapshot:
    """Immutable view of one model version, ready for compiled predict."""

    def __init__(self, *, stacked, groups, depth: int, n_groups: int,
                 base_score: np.ndarray, objective, num_features: int,
                 feature_names=None, cat_categories=None,
                 n_trees: int = 0) -> None:
        self.stacked = stacked          # dict of device arrays, or None (stump)
        self.groups = groups
        self.depth = depth
        self.n_groups = n_groups
        self.base_score = np.asarray(base_score, np.float32).reshape(-1)
        self.objective = objective
        self.num_features = num_features
        self.feature_names = list(feature_names) if feature_names else None
        self.cat_categories = cat_categories  # train-time {feat -> categories}
        self.n_trees = n_trees
        # bucket -> AOT-compiled fused serve program (warmcache.attach);
        # engine._execute prefers these — no trace, no compile, same bits
        self.aot_programs: dict = {}
        self._aot_base = None  # device copy of base_score for the AOT call

    def aot_execute(self, Xp_dev, output_margin: bool):
        """Run one bucket-padded batch through the AOT serve program for
        its row count (caller checked ``aot_programs``).  Returns the
        margin or transformed output, base score folded in."""
        if self._aot_base is None:
            import jax

            self._aot_base = jax.device_put(self.base_score)
        m, p = self.aot_programs[int(Xp_dev.shape[0])](
            Xp_dev, self.stacked, self.groups, self._aot_base)
        return m if output_margin else p

    # ------------------------------------------------------------ construct
    @classmethod
    def from_booster(cls, booster) -> "InferenceSnapshot":
        booster._configure()
        if booster.booster_kind == "gblinear":
            raise NotImplementedError(
                "serving snapshots cover tree boosters (gbtree/dart); "
                "gblinear is a single matmul — serve it directly")
        n_trees = len(booster.trees)
        if n_trees:
            # _stacked materializes on the default device (jnp.asarray), so
            # the first predict pays no host->device copy
            stacked, groups, depth = booster._stacked(slice(0, n_trees))
        else:
            stacked, groups, depth = None, None, 0
        base = np.broadcast_to(
            np.asarray(booster.base_score, np.float32).reshape(-1),
            (booster.n_groups,)).copy()
        return cls(
            stacked=stacked, groups=groups, depth=depth,
            n_groups=booster.n_groups, base_score=base,
            objective=booster.objective,
            num_features=booster.num_features(),
            feature_names=booster.feature_names,
            cat_categories=getattr(booster, "_cat_categories", None),
            n_trees=n_trees,
        )

    # -------------------------------------------------------------- predict
    def margin_padded(self, X_dev, init=None):
        """Raw ensemble margin for an already-bucket-padded (B, F) batch.
        Routes through the SAME jitted entry points as training eval, so the
        engine and the Booster share one compiled-program cache."""
        if self.stacked is None:
            import jax.numpy as jnp

            base = jnp.zeros((X_dev.shape[0], self.n_groups), jnp.float32)
            return base if init is None else base + init
        return run_stacked_margin(X_dev, self.stacked, self.groups,
                                  self.depth, self.n_groups, init)

    def margin(self, X_dev, init=None):
        """Bucket-pad, predict, slice — the direct (non-engine) entry."""
        R = X_dev.shape[0]
        bucket = bucket_rows(R)
        out = self.margin_padded(pad_rows(X_dev, bucket),
                                 pad_margin(init, bucket))
        return out if bucket == R else out[:R]

    def transform(self, margin):
        return self.objective.pred_transform(margin)

    @property
    def nbytes(self) -> int:
        if self.stacked is None:
            return 0
        return int(sum(v.nbytes for v in self.stacked.values()
                       if v is not None))

    def host_dense_recoded(self, dmat) -> np.ndarray:
        """DMatrix -> dense rows with categorical codes remapped onto the
        TRAIN-time category ordering (the same encoder/ordinal.h Recode step
        Booster.predict applies) — serving a frame whose pandas/arrow
        category order differs from training must not mis-route codes."""
        from ..data.dmatrix import recode_dense

        return recode_dense(dmat.host_dense(), self.cat_categories,
                            getattr(dmat, "cat_categories", None))

    def get_categories(self) -> Optional[dict]:
        """Train-time category mapping keyed by feature name (or index when
        unnamed) — the XGBoosterGetCategories payload."""
        from ..data.dmatrix import categories_by_name

        return categories_by_name(self.cat_categories, self.feature_names)
