"""Rendezvous tracker (reference: python-package/xgboost/tracker.py
RabitTracker binding src/collective/tracker.cc).

A real socket rendezvous server, not a shim: workers connect without knowing
their rank, the tracker assigns (rank, world) — sorted by host like the
reference's ``sortby="host"`` — and hands every worker the jax.distributed
coordinator address (the tracker allocates the port; rank 0 starts the
coordinator service inside ``jax.distributed.initialize``).  The persistent
tracker connection doubles as the ERROR CHANNEL: a worker reporting failure
(``collective.signal_error``) makes the tracker fan an abort out to every
other worker, whose watcher thread exits the process — the reference's
fail-fast elastic path (tracker.cc:345 CMD::kError handling +
comm.cc:340-376 detached error watcher calling std::exit).

Wire format: 4-byte big-endian length + JSON object.
"""
from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Dict, List, Optional, Union


def send_msg(sock: socket.socket, obj: dict) -> None:
    payload = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def recv_msg(sock: socket.socket) -> Optional[dict]:
    """One length-prefixed JSON message; None on clean EOF."""
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack(">I", hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return json.loads(buf.decode())


def get_host_ip(host_ip: str = "auto") -> str:
    if host_ip and host_ip != "auto":
        return host_ip
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
    except Exception:
        ip = "127.0.0.1"
    finally:
        s.close()
    return ip


class RabitTracker:
    """Socket rendezvous + error fan-out (reference surface: tracker.py:17 —
    start(), worker_args(), wait_for(), free())."""

    def __init__(self, n_workers: int, host_ip: str = "auto", port: int = 0,
                 sortby: str = "host", timeout: int = 0) -> None:
        self.n_workers = n_workers
        self.host_ip = get_host_ip(host_ip)
        self.sortby = sortby
        self.timeout = timeout
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host_ip, port))
        self.port = self._listener.getsockname()[1]
        self._conns: List[socket.socket] = []
        self._done = threading.Event()
        self._error: Optional[str] = None
        self._n_finished = 0
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- serving
    def start(self) -> None:
        self._listener.listen(self.n_workers)
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        pending = []  # (sort_key, arrival, conn)
        arrival = 0
        try:
            while len(pending) < self.n_workers:
                conn, _addr = self._listener.accept()
                # a stray connection (port scan, health check) must neither
                # consume a worker slot nor block the accept loop: bound the
                # handshake and drop anything that is not a start message
                conn.settimeout(30.0)
                try:
                    msg = recv_msg(conn)
                except (OSError, ValueError):
                    msg = None
                if not msg or msg.get("cmd") != "start":
                    conn.close()
                    continue
                conn.settimeout(None)
                key = (str(msg.get("task_id", "")) if self.sortby == "task"
                       else str(msg.get("host", "")))
                pending.append((key, arrival, conn))
                arrival += 1
        except OSError:
            return  # freed while accepting
        pending.sort(key=lambda t: (t[0], t[1]))
        self._conns = [c for (_k, _a, c) in pending]
        # rank 0 hosts the jax.distributed coordinator (it must BIND the
        # address, so the port cannot be allocated here on the tracker's
        # machine — multi-host topologies put them on different hosts):
        # two-phase bootstrap, rank 0 reports its coordinator address first
        r0_conn = self._conns[0]
        send_msg(r0_conn, {"rank": 0, "world": self.n_workers,
                           "coordinator": None})
        reply = recv_msg(r0_conn)
        if not reply or reply.get("cmd") != "coordinator":
            for c in self._conns:
                c.close()
            return
        coordinator = str(reply["addr"])
        for rank, conn in enumerate(self._conns[1:], start=1):
            send_msg(conn, {"rank": rank, "world": self.n_workers,
                            "coordinator": coordinator})
        for rank, conn in enumerate(self._conns):
            t = threading.Thread(target=self._watch_worker,
                                 args=(conn, rank), daemon=True)
            t.start()

    def _watch_worker(self, conn: socket.socket, rank: int) -> None:
        while True:
            try:
                msg = recv_msg(conn)
            except OSError:
                msg = None
            if msg is None or msg.get("cmd") == "shutdown":
                break
            if msg.get("cmd") == "error":
                # fan the failure out: every other worker aborts
                # (tracker.cc:345; workers' watchers exit on receipt)
                with self._lock:
                    if self._error is None:
                        self._error = (f"worker {rank}: "
                                       f"{msg.get('msg', 'unknown error')}")
                        for other in self._conns:
                            if other is not conn:
                                try:
                                    send_msg(other, {"cmd": "abort",
                                                     "msg": self._error})
                                except OSError:
                                    pass
                self._done.set()
                break
        with self._lock:
            self._n_finished += 1
            if self._n_finished >= self.n_workers:
                self._done.set()

    # ------------------------------------------------------------- client API
    def worker_args(self) -> Dict[str, Union[str, int]]:
        """Env for workers (consumed by collective.init tracker mode: no
        pre-assigned rank — the tracker hands one out)."""
        return {
            "dmlc_tracker_uri": self.host_ip,
            "dmlc_tracker_port": self.port,
            "dmlc_nworker": self.n_workers,
        }

    def wait_for(self, timeout: int = 0) -> None:
        ok = self._done.wait(timeout or self.timeout or None)
        if not ok:
            raise TimeoutError("tracker wait_for timed out")
        if self._error is not None:
            raise RuntimeError(f"tracker: training failed — {self._error}")

    def free(self) -> None:
        try:
            self._listener.close()
        except OSError:
            pass
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        self._done.set()


class TrackerClient:
    """Worker-side tracker connection: rendezvous + background abort watcher
    (the comm.cc:340-376 detached watcher thread role)."""

    def __init__(self, host: str, port: int, timeout: float = 120.0,
                 retries: int = 5, task_id: str = "") -> None:
        import time

        last = None
        for attempt in range(max(retries, 1)):
            try:
                self._sock = socket.create_connection((host, int(port)),
                                                      timeout=timeout)
                break
            except OSError as e:  # connect retry (comm.h:23 kRetry role);
                last = e          # backoff so workers racing the tracker's
                time.sleep(min(2.0 ** attempt, 10.0))  # start() can win
        else:
            raise ConnectionError(f"cannot reach tracker {host}:{port}: {last}")
        self._sock.settimeout(None)
        send_msg(self._sock, {"cmd": "start", "host": socket.gethostname(),
                              "task_id": task_id})
        reply = recv_msg(self._sock)
        if not reply or "rank" not in reply:
            raise ConnectionError("tracker rejected the start handshake")
        self.rank = int(reply["rank"])
        self.world = int(reply["world"])
        if reply.get("coordinator") is None:
            # rank 0: host the jax coordinator — allocate a port on THIS
            # machine and report it back (bind-then-close is a small TOCTOU
            # window; jax.distributed offers no way to hand over a bound
            # socket, so the race is accepted and retried at a higher level)
            my_ip = get_host_ip()
            with socket.socket() as s:
                s.bind((my_ip, 0))
                self.coordinator = f"{my_ip}:{s.getsockname()[1]}"
            send_msg(self._sock, {"cmd": "coordinator",
                                  "addr": self.coordinator})
        else:
            self.coordinator = str(reply["coordinator"])
        self._watcher = threading.Thread(target=self._watch, daemon=True)
        self._watcher.start()

    def _watch(self) -> None:
        while True:
            try:
                msg = recv_msg(self._sock)
            except OSError:
                return
            if msg is None:
                return
            if msg.get("cmd") == "abort":
                import os
                import sys

                print(f"[rank {self.rank}] aborting: peer failure — "
                      f"{msg.get('msg', '')}", file=sys.stderr, flush=True)
                os._exit(255)  # reference: std::exit(-1) in the watcher

    def signal_error(self, msg: str) -> None:
        try:
            send_msg(self._sock, {"cmd": "error", "msg": msg})
        except OSError:
            pass

    def shutdown(self) -> None:
        try:
            send_msg(self._sock, {"cmd": "shutdown"})
            self._sock.close()
        except OSError:
            pass
