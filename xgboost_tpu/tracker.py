"""Rendezvous tracker (reference: python-package/xgboost/tracker.py
RabitTracker binding src/collective/tracker.cc).

A real socket rendezvous server, not a shim: workers connect without knowing
their rank, the tracker assigns (rank, world) — sorted by host like the
reference's ``sortby="host"`` — and hands every worker the jax.distributed
coordinator address (the tracker allocates the port; rank 0 starts the
coordinator service inside ``jax.distributed.initialize``).  The persistent
tracker connection doubles as the ERROR CHANNEL: a worker reporting failure
(``collective.signal_error``) makes the tracker fan an abort out to every
other worker, whose watcher thread exits the process — the reference's
fail-fast elastic path (tracker.cc:345 CMD::kError handling +
comm.cc:340-376 detached error watcher calling std::exit).

Wire format: 4-byte big-endian length + 4-byte CRC-32 + JSON object; the
relay's raw binary payloads carry their CRC in the preceding ``coll`` /
``coll_result`` header.  Verification failures (and insane length
prefixes, which a flipped bit can produce) surface as
``ConnectionError`` — every caller already treats that as the peer being
gone, so a corrupted channel is quarantined exactly like a dropped one
instead of a damaged histogram folding into an allreduce
(docs/reliability.md "Integrity & chaos").  The ``tracker.message`` fault
seam in :func:`send_msg` injects deterministic byte flips to prove the
detection.

**Degraded links** (docs/reliability.md "Degraded networks"): the same
``tracker.message`` seam shapes outbound control traffic (``latency``
jitter, ``throttle`` pacing, ``blackhole_tx``/``partition`` silent
swallows) and a ``tracker.recv`` seam in :func:`recv_msg` consumes
inbound messages without delivering them (``blackhole_rx``/
``partition``) — together they model a half-open or partitioned link
whose TCP connection never errors.  Detection is layered: ``timeout`` in
:func:`recv_msg` is now a *cumulative* per-message deadline (the clock
starts at the first byte, so a slow-loris peer trickling one byte per
idle interval exhausts one budget), and ``XGBOOST_TPU_LINK_TIMEOUT_S``
arms a per-link collective deadline on the relay — much tighter than the
global stall ladder — that converts an asymmetric wedge (a rank whose
contributions vanish while it still hears us) into the ordinary elastic
regroup path within a bounded budget.
"""
from __future__ import annotations

import contextlib
import json
import os
import socket
import struct
import threading
import time
import warnings
import weakref
from typing import Any, Dict, List, Optional, Tuple, Union

from .elastic import RegroupRequired
from .reliability import lockdep as _lockdep

# sentinel returned by CollRelay._contribute while elastic membership is
# changing: the serve thread answers ``coll_regroup`` instead of a payload
_REGROUP = object()

# default bound on any single handshake/control send or recv: a hung peer
# mid-protocol becomes a detected fault (OSError/timeout at the caller)
# instead of a silent wedge.  Blocking reads that are SUPPOSED to wait
# forever — the abort-channel watchers — pass timeout=None explicitly.
OP_TIMEOUT = 300.0

# xtblint XTB902 contract (docs/static_analysis.md "Annotating an
# intentional ordering"): the client's collective lock is DESIGNED to be
# held across a full blocking protocol round — it serializes collectives
# on the one relay socket, and interrupt_collective() is the documented
# out-of-band escape that unblocks the holder without taking the lock
_XTB_SERIAL_LOCKS = ("TrackerClient._coll_lock", "RabitTracker._journal_io")


@contextlib.contextmanager
def _op_timeout(sock: socket.socket, timeout: Optional[float]):
    """Temporarily bound one socket operation (restores the prior mode)."""
    if timeout is None:
        yield
        return
    prev = sock.gettimeout()
    sock.settimeout(timeout)
    try:
        yield
    finally:
        try:
            sock.settimeout(prev)
        except OSError:
            pass  # peer closed the socket mid-operation


# bound on one control-channel JSON message (telemetry snapshots are the
# largest legitimate ones, ~100s of KB): a garbage length prefix must be
# a detected connection fault, not a 4 GiB allocation
MAX_MSG = 1 << 26

# per-link collective deadline (docs/reliability.md "Degraded networks"):
# when set (seconds), the relay declares a rank dead once a gather has
# been waiting on it this long past the FIRST contribution's arrival —
# converting an asymmetric wedge into the elastic regroup path in bounded
# time instead of waiting out op_timeout or a stall-watchdog budget.
# Unset = the global budgets own the case.
LINK_TIMEOUT_ENV = "XGBOOST_TPU_LINK_TIMEOUT_S"


def _link_timeout_s() -> Optional[float]:
    raw = os.environ.get(LINK_TIMEOUT_ENV, "").strip()
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


def _readmit_grace_s(link_timeout: Optional[float]) -> float:
    """Readmission window for a rank DECLARED lost by the per-link
    deadline: its severed channel is an invitation to rejoin, and the
    regroup the declaration triggered stays open this long waiting for
    the comeback (2x the link budget, clamped) — a healed asymmetric
    partition then restores the original world without committing a
    single round at reduced membership, which is what keeps the model
    bitwise-identical to a fault-free run."""
    base = 2.0 * (link_timeout if link_timeout else 1.0)
    return min(10.0, max(1.0, base))


def send_msg(sock: socket.socket, obj: dict,
             timeout: Optional[float] = None, *,
             peer: Any = None, trailing: bytes = b"") -> None:
    """One length-prefixed JSON message.  ``peer`` names the far end of
    the link (the worker rank on a tracker<->worker channel) for
    link-scoped fault matching at the ``tracker.message`` seam.
    ``trailing`` rides along as raw bytes AFTER the frame, under the
    same fault decision: a header announcing a payload and the payload
    itself must vanish (blackhole/partition) or be paced (throttle) as
    ONE unit — a swallowed header followed by loose payload bytes would
    desync the peer's framing, which is corruption, not a network
    fault."""
    import zlib

    from .reliability import faults as _faults

    payload = json.dumps(obj).encode()
    spec = _faults.maybe_inject("tracker.message", rank=peer)
    if spec is not None and spec.kind == "corrupt":
        # deterministic damage AFTER the CRC below is computed over the
        # ORIGINAL payload; scoped to the payload region (a flipped
        # length prefix is a stalled/insane peer, owned by the MAX_MSG
        # bound and the callers' operation timeouts)
        frame = (struct.pack(">II", len(payload), zlib.crc32(payload))
                 + _faults.corrupt_bytes(payload, spec))
    else:
        frame = (struct.pack(">II", len(payload), zlib.crc32(payload))
                 + payload)
    if spec is not None:
        if spec.kind == "blackhole_tx" or (
                spec.kind == "partition"
                and _faults.partition_blocks(spec, peer)):
            # half-open link, outbound side: the message vanishes, the
            # connection stays up — the peer must DETECT the silence
            # (link deadline, liveness ladder), which is the point
            return
        if spec.kind == "throttle":
            time.sleep(_faults.throttle_seconds(
                spec, len(frame) + len(trailing)))
    with _op_timeout(sock, timeout):
        sock.sendall(frame)
        if trailing:
            sock.sendall(trailing)


def recv_msg(sock: socket.socket,
             timeout: Optional[float] = None, *,
             peer: Any = None) -> Optional[dict]:
    """One length-prefixed JSON message; None on clean EOF.  ``timeout``
    bounds the WHOLE message *cumulatively*: each recv is bounded by it
    as a socket timeout AND the message must complete within it, clocked
    from the first byte's arrival — a slow-loris peer trickling one byte
    per idle interval exhausts one budget instead of resetting it per
    byte (socket.timeout is an OSError subclass, so existing error paths
    treat expiry either way as a connection fault).  A CRC mismatch or
    an insane length prefix raises ``ConnectionError`` — the corrupted
    channel is quarantined like a dropped one.  ``peer`` scopes rx-side
    fault matching (``tracker.recv`` seam), where ``blackhole_rx``/
    ``partition`` consume a message without delivering it."""
    import zlib

    from .reliability import faults as _faults

    while True:
        spec = _faults.maybe_inject("tracker.recv", rank=peer)
        deadline: Optional[float] = None
        with _op_timeout(sock, timeout):
            hdr = b""
            while len(hdr) < 8:
                chunk = sock.recv(8 - len(hdr))
                if not chunk:
                    return None
                if deadline is None and timeout is not None:
                    # the cumulative clock starts at the first byte —
                    # idle time between messages stays free
                    deadline = time.monotonic() + timeout
                hdr += chunk
                if (deadline is not None and len(hdr) < 8
                        and time.monotonic() >= deadline):
                    raise ConnectionError(
                        "tracker message header exceeded its cumulative "
                        "deadline (slow-loris bound) — dropping the "
                        "connection")
            n, crc = struct.unpack(">II", hdr)
            if n > MAX_MSG:
                from .reliability import integrity as _integrity

                _integrity.corrupt_detected("tracker")
                raise ConnectionError(
                    f"tracker message length {n} exceeds the {MAX_MSG} "
                    "bound (corrupted length prefix?) — dropping the "
                    "connection")
            buf = b""
            while len(buf) < n:
                chunk = sock.recv(n - len(buf))
                if not chunk:
                    return None
                buf += chunk
                if (deadline is not None and len(buf) < n
                        and time.monotonic() >= deadline):
                    raise ConnectionError(
                        f"tracker message body exceeded its cumulative "
                        f"deadline with {n - len(buf)} of {n} bytes "
                        "outstanding (slow-loris bound) — dropping the "
                        "connection")
        if zlib.crc32(buf) != crc:
            from .reliability import integrity as _integrity

            _integrity.corrupt_detected("tracker")
            raise ConnectionError(
                f"tracker message CRC mismatch ({n} bytes): corrupted in "
                "transit — dropping the connection")
        if spec is not None and (
                spec.kind == "blackhole_rx"
                or (spec.kind == "partition"
                    and _faults.partition_blocks(spec, peer))):
            # half-open link, inbound side: the kernel delivered the
            # message, the application never sees it — loop for the next
            continue
        return json.loads(buf.decode())


def get_host_ip(host_ip: str = "auto") -> str:
    if host_ip and host_ip != "auto":
        return host_ip
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    except OSError as e:
        warnings.warn(f"get_host_ip: cannot create a probe socket ({e}); "
                      "falling back to 127.0.0.1", RuntimeWarning,
                      stacklevel=2)
        return "127.0.0.1"
    try:
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
    except Exception as e:
        warnings.warn(f"get_host_ip: interface resolution failed ({e}); "
                      "falling back to 127.0.0.1", RuntimeWarning,
                      stacklevel=2)
        ip = "127.0.0.1"
    finally:
        s.close()
    return ip


def _crc32(data) -> int:
    import zlib

    return zlib.crc32(data)


def _recv_exact(sock: socket.socket, n: int,
                timeout: Optional[float] = None) -> bytes:
    """Exactly ``n`` raw bytes or OSError/ConnectionError (EOF counts)."""
    with _op_timeout(sock, timeout):
        chunks, got = [], 0
        while got < n:
            chunk = sock.recv(min(n - got, 1 << 20))
            if not chunk:
                raise ConnectionError("peer closed mid-payload")
            chunks.append(chunk)
            got += len(chunk)
    return b"".join(chunks)


class CollRelay:
    """Host-socket collective fallback: rank-ordered allgather through the
    tracker process.

    Why it exists: XLA's CPU backend (jaxlib < gloo support) cannot run
    multi-process collectives at all — ``jax.jit`` raises "Multiprocess
    computations aren't implemented on the CPU backend" — which would make
    tracker-mode CPU training (and every fault-injection test that needs
    real worker processes) impossible.  The relay carries the per-level
    histogram exchange over plain sockets instead: each worker sends
    (seq, payload); when all ``world`` contributions for a seq arrived, the
    rank-ordered concatenation goes back to every worker.  SPMD lockstep
    makes the seq numbering deterministic, and the host-side ordered
    reduction over the gathered stack keeps training bitwise reproducible
    (the same property the jax path has).

    Failure semantics are the tracker's: a worker EOF with an incomplete
    gather outstanding fails the collective for everyone (``coll_error``
    fan-out + the main-channel abort via ``on_worker_lost``); a completed
    worker closing its socket with nothing pending is a clean departure.
    Every send/recv is bounded by ``op_timeout`` so a hung peer is a
    detected fault, not a wedge.

    **Elastic mode** (``elastic=True``): membership is *epoch-tagged*.
    A lost rank no longer fails the job — the relay **flushes** every
    pending per-seq contribution (a dead worker's stale buffer must never
    fold into a later allreduce), answers blocked and future contributions
    with ``coll_regroup`` (workers raise
    :class:`~xgboost_tpu.elastic.RegroupRequired`), and waits for
    :meth:`regroup` to form the next epoch with the reduced (or grown)
    membership.  Contributions tagged with a stale epoch are rejected the
    same way, so a worker that raced the regroup can never mix epochs."""

    def __init__(self, host_ip: str, world: int,
                 op_timeout: float = 600.0, elastic: bool = False) -> None:
        self.world = world
        self.op_timeout = op_timeout
        self.elastic = bool(elastic)
        self.epoch = 0
        # per-link collective deadline (XGBOOST_TPU_LINK_TIMEOUT_S): once
        # the FIRST contribution of a gather arrives, a rank still
        # missing this many seconds later is declared lost and the epoch
        # regroups — asymmetric wedges convert to recovery in bounded
        # time instead of waiting out op_timeout (docs/reliability.md
        # "Degraded networks")
        self.link_timeout = _link_timeout_s()
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host_ip, 0))
        self.port = self._listener.getsockname()[1]
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: Dict[int, Dict[int, bytes]] = {}  # seq -> rank -> buf
        self._first_t: Dict[int, float] = {}  # seq -> first-arrival mono
        self._results: Dict[int, tuple] = {}  # seq -> (payload, refcount)
        self._departed: set = set()
        self._failed: Optional[str] = None
        self._regroup_pending = False
        self._closing = False
        self.on_worker_lost = None  # callback(rank, msg) -> abort fan-out
        self._slow_hist = None  # xtb_net_slow_peer_seconds, created lazily

    def _observe_slow_peer(self, rank: int, gap_s: float) -> None:
        """Slow-peer attribution: the gather's LAST contributor closed a
        ``gap_s``-second spread behind the first — the relay-side
        complement of the per-rank ``xtb_coll_wait_seconds`` view (the
        rank every OTHER rank burned that wall waiting for)."""
        if self._slow_hist is None:
            from .telemetry.registry import get_registry

            self._slow_hist = get_registry().histogram(
                "xtb_net_slow_peer_seconds", "spread between a gather's "
                "first and last contribution, attributed to the closing "
                "rank", ("rank",))
        self._slow_hist.labels(str(rank)).observe(gap_s)

    def start(self) -> None:
        self._listener.listen(self.world)
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # closed
            try:
                conn.settimeout(30.0)
                msg = recv_msg(conn)
                conn.settimeout(None)
            except (OSError, ValueError):
                conn.close()
                continue
            if not msg or msg.get("cmd") != "coll_join":
                conn.close()
                continue
            rank = int(msg["rank"])
            epoch = int(msg.get("epoch", 0))
            threading.Thread(target=self._serve_worker,
                             args=(conn, rank, epoch), daemon=True).start()

    def _fail(self, msg: str, lost_rank: Optional[int] = None) -> None:
        with self._cond:
            if self._failed is None and not self._closing:
                self._failed = msg
                self._cond.notify_all()
            else:
                return
        if lost_rank is not None and self.on_worker_lost is not None:
            self.on_worker_lost(lost_rank, msg)

    def _serve_worker(self, conn: socket.socket, rank: int,
                      epoch: int = 0) -> None:
        try:
            while True:
                try:
                    hdr = recv_msg(conn, peer=rank)
                except OSError:
                    hdr = None
                if hdr is None or hdr.get("cmd") != "coll":
                    break
                seq = int(hdr["seq"])
                buf = _recv_exact(conn, int(hdr["nbytes"]),
                                  timeout=self.op_timeout)
                if hdr.get("crc") is not None and _crc32(buf) != hdr["crc"]:
                    # a damaged contribution must NEVER fold into the
                    # gather: quarantine this worker's relay connection
                    # (the departure path below treats it as a lost peer)
                    from .reliability import integrity as _integrity

                    _integrity.corrupt_detected("tracker")
                    break
                result = self._contribute(seq, rank, buf, epoch)
                if result is _REGROUP:
                    # membership is changing: the worker raises
                    # RegroupRequired and reconnects on the next epoch
                    send_msg(conn, {"cmd": "coll_regroup",
                                    "epoch": self.epoch}, timeout=30.0,
                             peer=rank)
                    break
                if result is None:
                    send_msg(conn, {"cmd": "coll_error",
                                    "msg": self._failed or "relay failed"},
                             timeout=30.0, peer=rank)
                    break
                send_msg(conn, {"cmd": "coll_result", "seq": seq,
                                "nbytes": len(result),
                                "crc": _crc32(result)},
                         timeout=self.op_timeout, peer=rank,
                         trailing=result)
        except OSError:
            pass
        finally:
            if self.elastic:
                self._elastic_departure(rank, epoch)
            else:
                incomplete = False
                with self._cond:
                    self._departed.add(rank)
                    # only gathers still MISSING this rank's payload are
                    # doomed; one it already fed can complete for survivors
                    incomplete = (not self._closing
                                  and any(rank not in contribs
                                          for contribs
                                          in self._pending.values()))
                    self._cond.notify_all()  # wake waiters to run the check
                if incomplete and self._failed is None:
                    # this worker can no longer contribute to an outstanding
                    # gather: everyone blocked on it must fail fast
                    self._fail(f"collective peer {rank} lost mid-gather",
                               lost_rank=rank)
            conn.close()

    def _elastic_departure(self, rank: int, epoch: int) -> None:
        """Elastic worker-socket EOF: flush gathers the departed rank can no
        longer feed (its stale partial contributions must never reach the
        next epoch's allreduce) and hand the loss to the tracker, which
        initiates the regroup.  A stale-epoch or mid-regroup departure is a
        worker reconnecting — membership bookkeeping already moved on."""
        lost_mid_gather = False
        with self._cond:
            if (not self._closing and epoch == self.epoch
                    and not self._regroup_pending):
                self._departed.add(rank)
                lost_mid_gather = any(rank not in contribs
                                      for contribs in self._pending.values())
                if lost_mid_gather:
                    self._regroup_pending = True
                    self._pending.clear()
                    self._first_t.clear()
                    self._results.clear()
                self._cond.notify_all()
        if lost_mid_gather and self.on_worker_lost is not None:
            self.on_worker_lost(rank, "collective peer lost; regrouping")

    def invalidate(self, epoch: Optional[int] = None) -> None:
        """Flush every in-flight gather and answer all contributions with
        ``coll_regroup`` until :meth:`regroup` forms the next epoch.  The
        tracker calls this the moment it starts a regroup — also for pure
        absorption (no death): a worker that checked its round boundary a
        microsecond before the announcement would otherwise enter a gather
        its already-regrouping peers never join.

        ``epoch`` guards against the stale-invalidation race: the members'
        ``regroup_join``\\s can complete the regroup (on watcher threads)
        while the detecting thread is still on its way here, and an
        unconditional flush would then poison the epoch that was just
        formed.  Pass the epoch the invalidation was captured under; a
        mismatch means that membership change already completed."""
        with self._cond:
            if self._closing:
                return
            if epoch is not None and epoch != self.epoch:
                return  # that regroup already formed the next epoch
            self._regroup_pending = True
            self._pending.clear()
            self._first_t.clear()
            self._results.clear()
            self._cond.notify_all()

    def regroup(self, world: int, epoch: int) -> None:
        """Form the next epoch: new membership size, fresh buffers, stale
        departure state cleared.  Workers reconnect with the new epoch tag
        and restart their seq numbering at 0."""
        with self._cond:
            self.world = int(world)
            self.epoch = int(epoch)
            self._pending.clear()
            self._first_t.clear()
            self._results.clear()
            self._departed.clear()
            self._failed = None
            self._regroup_pending = False
            self._cond.notify_all()

    def _contribute(self, seq: int, rank: int, buf: bytes,
                    epoch: int = 0):
        """Add ``rank``'s payload; block until the gather completes; returns
        the rank-ordered concatenation, ``_REGROUP`` when membership is
        changing (elastic), or None on failure/timeout.  With a per-link
        deadline armed (``XGBOOST_TPU_LINK_TIMEOUT_S``), ranks still
        missing that long after the gather's FIRST contribution are
        declared lost and the epoch regroups — the bounded conversion of
        an asymmetric wedge into recovery."""
        deadline = time.monotonic() + self.op_timeout
        wedged: Optional[list] = None
        with self._cond:
            if self.elastic and (self._regroup_pending
                                 or epoch != self.epoch):
                return _REGROUP
            self._pending.setdefault(seq, {})[rank] = buf
            first_t = self._first_t.setdefault(seq, time.monotonic())
            while True:
                if self.elastic and (self._regroup_pending
                                     or epoch != self.epoch):
                    return _REGROUP
                if self._failed is not None or self._closing:
                    return None
                got = self._pending.get(seq)
                if got is not None and len(got) == self.world:
                    payload = b"".join(got[r] for r in range(self.world))
                    del self._pending[seq]
                    # slow-peer attribution: THIS call closed the gather,
                    # so the spread behind the first arrival is this
                    # rank's to own
                    self._observe_slow_peer(
                        rank, time.monotonic()
                        - self._first_t.pop(seq, first_t))
                    self._results[seq] = (payload, self.world)
                    self._cond.notify_all()
                if seq in self._results:
                    payload, refs = self._results[seq]
                    if refs <= 1:
                        del self._results[seq]
                    else:
                        self._results[seq] = (payload, refs - 1)
                    return payload
                if got is not None and any(d not in got
                                           for d in self._departed):
                    # a missing contributor is gone: can never finish
                    if self.elastic:
                        # the epoch is doomed, not the job: flush and
                        # steer every blocked worker into the regroup
                        self._regroup_pending = True
                        self._pending.clear()
                        self._first_t.clear()
                        self._results.clear()
                        self._cond.notify_all()
                        return _REGROUP
                    break
                if (self.elastic and self.link_timeout is not None
                        and got is not None
                        and time.monotonic() - first_t
                        > self.link_timeout):
                    # per-link deadline: somebody contributed link_timeout
                    # seconds ago and these ranks still have not — their
                    # links are wedged (half-open, partitioned, or the
                    # peer is glacial).  Declare them lost NOW so the
                    # survivors regroup within the link budget instead of
                    # the op_timeout/watchdog horizon.
                    wedged = sorted(set(range(self.world)) - set(got)
                                    - self._departed)
                    self._departed.update(wedged)
                    self._regroup_pending = True
                    self._pending.clear()
                    self._first_t.clear()
                    self._results.clear()
                    self._cond.notify_all()
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cond.wait(timeout=min(left, 5.0))
        if wedged is not None:
            for lost in wedged:
                if self.on_worker_lost is not None:
                    # declared=True: the peer may well be alive behind a
                    # wedged link — the tracker severs it expecting either
                    # a corpse or a comeback (readmission grace)
                    self.on_worker_lost(
                        lost, f"collective link deadline "
                        f"({self.link_timeout:g}s) exceeded: rank {lost} "
                        f"never contributed to seq {seq}", True)
            return _REGROUP
        self._fail(f"collective seq {seq} incomplete "
                   f"(departed={sorted(self._departed)})")
        return None

    def close(self) -> None:
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        try:
            self._listener.close()
        except OSError:
            pass


class RabitTracker:
    """Socket rendezvous + error fan-out (reference surface: tracker.py:17 —
    start(), worker_args(), wait_for(), free()).

    **Elastic mode** (``elastic=True``, docs/reliability.md § Elastic
    training): a worker whose connection drops no longer aborts the job.
    Instead the tracker initiates a **regroup**: the relay is invalidated
    (in-flight collectives surface ``RegroupRequired`` on every survivor),
    ``regroup_pending`` is announced on the persistent channel, and once
    every live worker has sent ``regroup_join`` from its round boundary
    the tracker assigns compacted ``(rank, world)`` pairs — survivors
    ordered by their previous rank, then any late joiners — bumps the
    epoch, re-forms the relay, and replies with the new membership.  A
    replacement worker simply connects with the normal ``start``
    handshake after rendezvous; it is parked and absorbed by the next
    regroup, its handshake answered with the elastic assignment
    (including the round to resume from).  Explicit ``signal_error`` still
    aborts everyone — elasticity forgives *deaths*, not reported bugs."""

    def __init__(self, n_workers: int, host_ip: str = "auto", port: int = 0,
                 sortby: str = "host", timeout: int = 0,
                 handshake_timeout: float = OP_TIMEOUT,
                 elastic: bool = False,
                 journal: Optional[str] = None) -> None:
        self.n_workers = n_workers
        self.host_ip = get_host_ip(host_ip)
        self.sortby = sortby
        self.timeout = timeout
        self.handshake_timeout = handshake_timeout
        self.elastic = bool(elastic)
        self._closing = False
        # --- coordinator failover (docs/reliability.md "Coordinator
        # failover & watchdog"): with a journal armed, every membership
        # transition is fsync'd, and a respawned tracker recovers the
        # roster/epoch and re-adopts the surviving workers instead of the
        # job dying with the coordinator.
        self._journal = None
        self._recovered: Optional[dict] = None
        if journal:
            from .reliability.journal import TrackerJournal

            self._journal = TrackerJournal(journal)
            # repair: a SIGKILL mid-append leaves a torn tail; truncating
            # it keeps OUR appends reachable by the NEXT recovery's walk
            state = self._journal.load(count_recovery=True, repair=True)
            if state and state.get("members"):
                self._recovered = state
                if port == 0:
                    # rebind the predecessor's port: the workers only know
                    # that address
                    port = int(state.get("port", 0))
        self._relay = CollRelay(self.host_ip, n_workers,
                                elastic=self.elastic)
        self._relay.on_worker_lost = self._relay_worker_lost
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host_ip, port))
        self.port = self._listener.getsockname()[1]
        self._conns: List[socket.socket] = []
        self._done = threading.Event()
        self._error: Optional[str] = None
        self._lock = threading.Lock()
        # per-connection control-send locks (fleet's txlock idiom): sends
        # happen with _lock RELEASED so a wedged peer cannot stall the
        # membership state, but concurrent senders to one socket must not
        # shear a frame.  Weak keys: entries die with their socket.
        self._ctl_tx: "weakref.WeakKeyDictionary[socket.socket, threading.Lock]" = \
            weakref.WeakKeyDictionary()
        # journal append serialization (_XTB_SERIAL_LOCKS): entries must
        # land in state-capture order, so capture+append pairs run under
        # this lock — NOT under _lock, which must never be held across
        # the disk write (a slow disk stalls the journal, not liveness)
        self._journal_io = _lockdep.mark_serial(threading.Lock())
        self._thread: Optional[threading.Thread] = None
        # --- membership state (all guarded by _lock) ---
        self._members: Dict[socket.socket, int] = {}  # live conn -> rank
        self._watched: set = set()      # conns with a running watcher
        self._serve_done = False        # initial rendezvous complete
        self._clean_exits = 0
        self._epoch = 0
        self._regrouping = False
        self._regroup_t0 = 0.0
        self._regroup_joins: Dict[socket.socket, int] = {}  # conn -> round
        self._joiners: List[socket.socket] = []  # parked replacement conns
        # readmission grace (link-deadline declarations only): how many
        # declared-lost ranks the pending regroup still waits a comeback
        # from, and until when (monotonic) it may wait
        self._readmit_waiting = 0
        self._readmit_until = 0.0
        self._readmit_timer = False
        self._readmit_ins = None  # xtb_net_readmissions_total, lazy
        self.lost_workers = 0
        # last shipped telemetry payload per source label ("rank<N>"):
        # retained after the worker dies (postmortem + merged scrape)
        self.telemetry: Dict[str, dict] = {}
        # --- failover/watchdog state (guarded by _lock) ---
        self._readopt_pending: set = set()   # ranks a recovery still awaits
        self._readopt_deadline = 0.0
        self._progress_round: Dict[int, int] = {}  # rank -> last round seen
        self._shard_map: Optional[dict] = None     # latest reported map
        self._liveness: Dict[int, dict] = {}  # rank -> markers/t_advance/stage
        self._join_stage: Dict[socket.socket, int] = {}
        self._journal_last = 0.0
        if self._recovered is not None:
            self._epoch = int(self._recovered.get("epoch", 0))
            self._progress_round = {
                int(r): int((m or {}).get("round", 0))
                for r, m in self._recovered.get("members", {}).items()}
            self._shard_map = self._recovered.get("shard_map")

    # ------------------------------------------------------------- serving
    def start(self) -> None:
        self._listener.listen(self.n_workers)
        self._relay.start()
        t = threading.Thread(
            target=(self._serve_recovery if self._recovered is not None
                    else self._serve), daemon=True)
        with self._lock:
            self._thread = t
        t.start()
        if self.elastic:
            # always started: the loop also enforces the readopt deadline,
            # which is failover CORRECTNESS (a never-returning rank must be
            # pruned or the recovery regroup cannot form) — only the stall
            # ladders inside honor the XGBOOST_TPU_WATCHDOG kill switch
            threading.Thread(target=self._watchdog_loop,
                             daemon=True).start()

    # ------------------------------------------------------ journal writes
    def _journal_state(self) -> dict:
        """The replayable coordinator state (``_lock`` must be held):
        roster with per-rank resume rounds, epoch, shard map, pending
        regroup — everything a respawned tracker needs to re-adopt the
        survivors; model state stays in the elastic checkpoints."""
        ranks = sorted(self._members.values())
        return {
            "version": 1,
            "port": self.port,
            "n_workers": self.n_workers,
            "elastic": self.elastic,
            "sortby": self.sortby,
            "epoch": self._epoch,
            "regrouping": bool(self._regrouping),
            "members": {str(r): {"round": self._progress_round.get(r, 0)}
                        for r in ranks},
            "shard_map": self._shard_map,
        }

    def _journal_write(self, force: bool = False) -> None:
        """Commit the current state to the journal.  Membership
        transitions pass ``force``; progress-marker refreshes are
        throttled so the fsync cadence stays bounded however chatty the
        telemetry channel is."""
        if self._journal is None:
            return
        now = time.monotonic()
        # io lock OUTSIDE the state lock (_journal_io -> _lock order):
        # holding it across capture+append keeps entries in state-capture
        # order (replay trusts the LAST entry, so an older capture landing
        # after a newer one would resurrect stale state on respawn)
        with self._journal_io:
            with self._lock:
                if not self._serve_done and self._recovered is None:
                    return  # no roster yet: nothing replayable to record
                if not force and now - self._journal_last < 1.0:
                    return
                self._journal_last = now
                state = self._journal_state()
            try:
                self._journal.append(state)
            except OSError as e:  # journal loss degrades failover, not job
                warnings.warn(f"tracker journal write failed ({e}); a "
                              "tracker respawn may not recover this "
                              "transition", RuntimeWarning, stacklevel=2)

    def _serve(self) -> None:
        pending = []  # (sort_key, arrival, conn)
        arrival = 0
        try:
            while len(pending) < self.n_workers:
                conn, _addr = self._listener.accept()
                # a stray connection (port scan, health check) must neither
                # consume a worker slot nor block the accept loop: bound the
                # handshake and drop anything that is not a start message
                conn.settimeout(30.0)
                try:
                    msg = recv_msg(conn)
                except (OSError, ValueError):
                    msg = None
                if (msg and msg.get("cmd") == "readopt"
                        and self._journal is not None):
                    # the predecessor died in the window between handing
                    # out assignments and journaling the roster: the
                    # re-adopting workers ARE the roster (ranks 0..n-1
                    # were just assigned) — run the recovery protocol
                    # with the full expected set instead of refusing
                    conn.settimeout(None)
                    with self._lock:
                        # 'start' handshakes already collected are NOT
                        # part of the dead cohort — park them as joiners
                        # so the recovery regroup absorbs (and answers)
                        # them instead of leaving them blocked unreplied
                        for _k, _a, c in pending:
                            self._joiners.append(c)
                            self._conns.append(c)
                    pending = []
                    self._begin_unjournaled_recovery()
                    self._handle_readopt(conn, msg)
                    self._accept_post()
                    return
                if not msg or msg.get("cmd") != "start":
                    conn.close()
                    continue
                conn.settimeout(None)
                key = (str(msg.get("task_id", "")) if self.sortby == "task"
                       else str(msg.get("host", "")))
                pending.append((key, arrival, conn))
                arrival += 1
        except OSError:
            return  # freed while accepting
        pending.sort(key=lambda t: (t[0], t[1]))
        conns = [c for (_k, _a, c) in pending]
        with self._lock:
            # publish under the lock: _fan_abort iterates _conns from other
            # threads the moment the watchers below start
            self._conns = conns
        # rank 0 hosts the jax.distributed coordinator (it must BIND the
        # address, so the port cannot be allocated here on the tracker's
        # machine — multi-host topologies put them on different hosts):
        # two-phase bootstrap, rank 0 reports its coordinator address first
        r0_conn = self._conns[0]
        failover = self._journal is not None
        try:
            # bounded two-phase bootstrap: a rank 0 that connects and then
            # hangs must surface as a handshake failure, not wedge the
            # tracker (and every other worker) forever
            send_msg(r0_conn, {"rank": 0, "world": self.n_workers,
                               "coordinator": None,
                               "coll_port": self._relay.port,
                               "failover": failover,
                               "elastic": self.elastic},
                     timeout=self.handshake_timeout, peer=0)
            reply = recv_msg(r0_conn, timeout=self.handshake_timeout,
                             peer=0)
        except OSError:
            reply = None
        if not reply or reply.get("cmd") != "coordinator":
            with self._lock:
                if self._error is None:
                    self._error = ("worker 0: coordinator handshake failed "
                                   "or timed out")
            for c in self._conns:
                c.close()
            self._done.set()
            return
        coordinator = str(reply["addr"])
        for rank, conn in enumerate(self._conns[1:], start=1):
            try:
                send_msg(conn, {"rank": rank, "world": self.n_workers,
                                "coordinator": coordinator,
                                "coll_port": self._relay.port,
                                "failover": failover,
                                "elastic": self.elastic},
                         timeout=self.handshake_timeout, peer=rank)
            except OSError:
                pass  # the worker's watcher EOF-detection handles its death
        with self._lock:
            self._members = {conn: rank
                             for rank, conn in enumerate(self._conns)}
            self._watched = set(self._conns)
            self._serve_done = True
        self._journal_write(force=True)  # the roster is now replayable
        for rank, conn in enumerate(self._conns):
            t = threading.Thread(target=self._watch_worker,
                                 args=(conn, rank), daemon=True)
            t.start()
        if self.elastic:
            # keep the listener open: replacement workers connect with the
            # same start handshake and are absorbed at the next regroup
            threading.Thread(target=self._accept_late, daemon=True).start()

    def _serve_recovery(self) -> None:
        """Respawned-tracker serving: no rendezvous — the journaled roster
        IS the cohort.  The tracker opens in a pending regroup, accepts
        ``readopt`` handshakes from the journaled ranks (and ordinary
        ``start`` handshakes from replacements, parked as usual), and the
        re-adoption completes through the NORMAL regroup machinery: every
        re-adopted worker sends ``regroup_join`` from its round boundary,
        the epoch bumps, the relay re-forms, training resumes from the
        newest checkpoint.  A rank that never re-adopts (it died with —
        or because of — the old tracker) is declared dead at the readopt
        deadline and the epoch forms with the remainder."""
        import os as _os

        state = self._recovered or {}
        expected = {int(r) for r in state.get("members", {})}
        try:
            deadline_s = float(_os.environ.get(
                "XGBOOST_TPU_READOPT_TIMEOUT_S", "60"))
        except ValueError:
            deadline_s = 60.0
        with self._lock:
            self._serve_done = True
            self._regrouping = True
            self._regroup_t0 = time.perf_counter()
            self._readopt_pending = set(expected)
            self._readopt_deadline = time.monotonic() + deadline_s
        from .telemetry import flight as _flight

        _flight.record("event", "tracker.recovery", epoch=self._epoch,
                       expected=sorted(expected))
        warnings.warn(
            f"tracker recovered from journal: epoch {self._epoch}, "
            f"awaiting re-adoption of rank(s) {sorted(expected)}",
            RuntimeWarning, stacklevel=2)
        self._accept_post()

    def _begin_unjournaled_recovery(self) -> None:
        """Open a recovery for a cohort the journal never recorded (the
        predecessor was killed pre-first-write): expect every originally
        assigned rank; the readopt deadline prunes the ones that died."""
        import os as _os

        try:
            deadline_s = float(_os.environ.get(
                "XGBOOST_TPU_READOPT_TIMEOUT_S", "60"))
        except ValueError:
            deadline_s = 60.0
        with self._lock:
            self._serve_done = True
            self._regrouping = True
            self._regroup_t0 = time.perf_counter()
            self._readopt_pending = set(range(self.n_workers))
            self._readopt_deadline = time.monotonic() + deadline_s
        from .telemetry import flight as _flight

        _flight.record("event", "tracker.recovery_unjournaled",
                       expected=self.n_workers)
        warnings.warn(
            "tracker respawned with no journaled roster (predecessor died "
            "pre-first-write); re-adopting the assigned cohort",
            RuntimeWarning, stacklevel=2)

    def _declare_readopt_deadline(self) -> None:
        """Readopt deadline passed: the ranks that never came back died
        with the old tracker — stop waiting for them so the survivors'
        regroup can form."""
        with self._lock:
            missing = set(self._readopt_pending)
            if not missing:
                return
            self._readopt_pending = set()
            self.lost_workers += len(missing)
        from .reliability import watchdog as _watchdog

        for rank in sorted(missing):
            _watchdog.note("tracker.peer", "stall", rank=rank,
                           reason="never re-adopted after tracker recovery")
        self._maybe_complete_regroup()

    def _send_ctl(self, conn: socket.socket, payload: dict, *,
                  timeout: float, peer: Optional[int] = None) -> None:
        """Control-plane send with the state lock NOT held (XTB902): a
        wedged peer stalls only its own connection, never the membership
        state every watcher/tick needs.  The per-connection tx lock keeps
        concurrent control frames to one socket from shearing."""
        with self._lock:
            lk = self._ctl_tx.get(conn)
            if lk is None:
                # serialization lock: held across the wire send by
                # contract, so the lockdep witness must not flag it
                lk = self._ctl_tx[conn] = _lockdep.mark_serial(
                    threading.Lock())
        with lk:
            send_msg(conn, payload, timeout=timeout, peer=peer)

    def _fan_abort(self, rank: int, msg: str,
                   source: Optional[socket.socket]) -> None:
        """First failure wins: record it and abort every OTHER worker
        (tracker.cc:345; workers' watchers exit on receipt)."""
        targets: List[Tuple[socket.socket, Optional[int]]] = []
        err = ""
        with self._lock:
            if self._error is None:
                self._error = err = f"worker {rank}: {msg}"
                targets = [(other, self._members.get(other))
                           for other in self._conns if other is not source]
        for other, peer in targets:
            try:
                self._send_ctl(other, {"cmd": "abort", "msg": err},
                               timeout=30.0, peer=peer)
            except OSError:
                pass
        self._done.set()

    def _watch_worker(self, conn: socket.socket, rank: int) -> None:
        clean = False
        while True:
            try:
                msg = recv_msg(conn, peer=rank)
            except OSError:
                msg = None
            if msg is None:
                break
            if msg.get("cmd") == "shutdown":
                clean = True
                break
            if msg.get("cmd") == "error":
                with self._lock:
                    cur = self._members.get(conn, rank)
                self._fan_abort(cur, msg.get("msg", "unknown error"), conn)
                break
            if msg.get("cmd") == "regroup_join" and self.elastic:
                self._handle_regroup_join(conn, int(msg.get("round", 0)))
                continue
            if msg.get("cmd") == "telemetry":
                # metric shipping over the persistent channel: ingest the
                # worker's registry snapshot + flight ring driver-side
                # under its CURRENT rank (dead workers keep their last)
                with self._lock:
                    cur = self._members.get(conn, rank)
                self._ingest_telemetry(cur, msg)
                continue
        if clean:
            stranded: List[socket.socket] = []
            with self._lock:
                self._members.pop(conn, None)
                self._clean_exits += 1
                if not self._members and self._joiners:
                    # training finished with replacements still parked:
                    # there is nothing left to absorb them into
                    stranded = self._joiners
                    self._joiners = []
                    # the regroup those joiners triggered can never form —
                    # a stale flag here would turn the clean finish into a
                    # spurious "regroup with no members" error
                    self._regrouping = False
                    self._regroup_joins = {}
                    self._readmit_waiting = 0
                    self._readmit_until = 0.0
            for j in stranded:
                try:
                    self._send_ctl(j, {"cmd": "abort",
                                       "msg": "training already complete"},
                                   timeout=5.0)
                except OSError:
                    pass
                try:
                    j.close()
                except OSError:
                    pass
            if self.elastic:
                self._journal_write(force=True)
                # a clean exit during a pending regroup: the remaining
                # members must not wait for this worker's join
                self._maybe_complete_regroup()
        elif not self._closing and self._error is None:
            if self.elastic:
                # elastic: a silent death shrinks the world instead of
                # ending the job — regroup the survivors
                with self._lock:
                    cur = self._members.get(conn, rank)
                self._on_worker_death(conn, cur,
                                      "tracker connection lost "
                                      "(worker process died)")
            else:
                # EOF without a shutdown message: the worker DIED (crash,
                # SIGKILL, machine loss) without getting to signal_error.
                # Its peers are blocked in a collective waiting for it —
                # fan the abort out so they fail fast instead of wedging
                # (the Rabit lineage treats a lost tracker connection
                # exactly this way).
                self._fan_abort(rank, "tracker connection lost "
                                "(worker process died)", conn)
        with self._lock:
            self._watched.discard(conn)
            finished = (self._serve_done and not self._watched
                        and not self._joiners)
            if finished and self._clean_exits == 0 and self._error is None:
                self._error = "all workers lost (no clean shutdowns)"
        if finished:
            self._done.set()

    def _ingest_telemetry(self, rank: int, msg: dict) -> None:
        """One worker telemetry shipment: keep the last payload per rank
        and feed the snapshot into the process-default merged registry so
        a driver-side ``/metrics`` scrape shows every rank's series
        (telemetry/distributed.py; docs/observability.md).  Piggybacked
        watchdog progress markers feed the stall monitor and the journal's
        per-rank resume rounds."""
        source = f"rank{rank}"
        payload = {"snapshot": msg.get("snapshot"),
                   "flight": msg.get("flight") or [],
                   "profile": msg.get("profile"),
                   "pid": msg.get("pid")}
        with self._lock:
            self.telemetry[source] = payload
        marks = msg.get("progress")
        if isinstance(marks, dict) and marks:
            self._ingest_progress(rank, marks)
        try:
            from .telemetry.distributed import get_merged

            # snapshot + flight ring + profiler stacks per rank: the
            # merged flame view and /flight endpoint read these back
            get_merged().ingest_payload(source, payload)
        except Exception:  # pragma: no cover - telemetry must not kill
            pass           # the rendezvous channel

    def _ingest_progress(self, rank: int, marks: dict) -> None:
        """One rank's liveness markers.  The staleness clock only resets
        when the markers ADVANCED — a shipment carrying the same frozen
        markers is a heartbeat (the channel is up) but not progress, and
        only progress keeps a peer off the stall ladder
        (tests/test_watchdog.py pins the distinction)."""
        from .reliability import watchdog as _watchdog

        with self._lock:
            ent = self._liveness.get(rank)
            if ent is None or _watchdog.advanced(ent.get("markers"), marks):
                self._liveness[rank] = {"markers": marks,
                                        "t_advance": time.monotonic(),
                                        "stage": 0}
            tr = marks.get("train.round")
            if isinstance(tr, dict) and tr.get("round") is not None:
                try:
                    self._progress_round[rank] = max(
                        self._progress_round.get(rank, 0),
                        int(tr["round"]))
                except (TypeError, ValueError):
                    pass
            sm = marks.get("shard_map")
            if isinstance(sm, dict) and isinstance(sm.get("map"), dict):
                self._shard_map = sm["map"]
        self._journal_write()  # throttled: resume rounds stay fresh

    # --------------------------------------------------- stall watchdog
    def _watchdog_loop(self) -> None:
        """Tracker-side stall monitor (elastic mode): two deterministic
        ladders over the watchdog budgets, both ending in an EXISTING
        recovery path —

        - ``tracker.join``: a member that has not reached its round
          boundary while a regroup is pending (warn → request a remote
          stack dump → declare it dead, so the epoch forms with the
          remainder instead of everyone waiting forever);
        - ``tracker.peer``: a rank whose progress markers froze while at
          least one peer kept advancing (same ladder — a stalled-but-
          alive worker becomes a detected death, and the regroup fires).

        Plus the readopt deadline after a tracker recovery."""
        from .reliability import watchdog as _watchdog

        while True:
            time.sleep(0.25)
            with self._lock:
                if self._closing or self._error is not None:
                    return
                deadline = self._readopt_deadline
                pending = bool(self._readopt_pending)
            if pending and time.monotonic() > deadline:
                self._declare_readopt_deadline()
            if _watchdog.enabled():
                self._check_join_stalls(_watchdog)
                self._check_peer_stalls(_watchdog)

    def _escalate_member(self, watchdog, seam: str, stage: int,
                         conn: socket.socket, rank: int,
                         elapsed: float) -> None:
        """One ladder step against a live member: warn, ask it for an
        all-thread stack dump (its watcher thread answers even when the
        main thread is wedged), or close its channel — the EOF runs the
        ordinary elastic death path, so 'declared dead' and 'actually
        dead' recover identically."""
        stage_name = watchdog.STAGES[stage - 1]
        watchdog.note(seam, stage_name, rank=rank,
                      elapsed_s=round(elapsed, 3))
        if stage_name == "dump":
            try:
                send_msg(conn, {"cmd": "stackdump",
                                "reason": f"{seam} watchdog"}, timeout=5.0,
                         peer=rank)
            except OSError:
                pass
        elif stage_name == "stall":
            # shutdown() (not close()) is what reliably wakes the watcher
            # thread blocked in recv on this socket: its EOF then runs
            # the ordinary elastic death path
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def _check_join_stalls(self, watchdog) -> None:
        budget = watchdog.budget_for("tracker.join")
        thresholds = (watchdog.WARN_AT, watchdog.DUMP_AT, watchdog.STALL_AT)
        laggards = []
        with self._lock:
            if not self._regrouping or self._readopt_pending:
                if self._join_stage:
                    self._join_stage = {}
                return
            elapsed = time.perf_counter() - self._regroup_t0
            for conn, rank in self._members.items():
                if conn in self._regroup_joins:
                    continue
                stage = self._join_stage.get(conn, 0)
                while (stage < len(thresholds)
                       and elapsed >= budget * thresholds[stage]):
                    stage += 1
                    laggards.append((conn, rank, stage, elapsed))
                self._join_stage[conn] = stage
        for conn, rank, stage, elapsed in laggards:
            self._escalate_member(watchdog, "tracker.join", stage, conn,
                                  rank, elapsed)

    def _check_peer_stalls(self, watchdog) -> None:
        budget = watchdog.budget_for("tracker.peer")
        thresholds = (watchdog.WARN_AT, watchdog.DUMP_AT, watchdog.STALL_AT)
        now = time.monotonic()
        due = []
        with self._lock:
            if self._regrouping:
                return  # the join ladder owns a draining membership change
            if len(self._liveness) < 2:
                return  # nothing to compare a suspect against
            newest = max(e["t_advance"] for e in self._liveness.values())
            if now - newest > budget:
                # only a DIVERGING stall escalates: if nobody advanced
                # within the budget the whole job is in one long legit
                # phase (a big collective, a huge page) — not a stall
                return
            for rank, ent in self._liveness.items():
                age = now - ent["t_advance"]
                stage = ent.get("stage", 0)
                while (stage < len(thresholds)
                       and age >= budget * thresholds[stage]):
                    stage += 1
                    conn = next((c for c, r in self._members.items()
                                 if r == rank), None)
                    if conn is not None:
                        due.append((conn, rank, stage, age))
                ent["stage"] = stage
        for conn, rank, stage, age in due:
            self._escalate_member(watchdog, "tracker.peer", stage, conn,
                                  rank, age)

    # ------------------------------------------------- elastic membership
    def _accept_late(self) -> None:
        """Post-rendezvous accept loop (elastic only) — see
        :meth:`_accept_post` (shared with the recovery path)."""
        self._accept_post()

    def _accept_post(self) -> None:
        """Post-rendezvous/recovery accept loop: a ``start`` handshake is
        a replacement worker (parked, absorbed at the next regroup); a
        ``readopt`` handshake is a survivor of a tracker respawn
        reclaiming its journaled rank (recovery only — outside a pending
        re-adoption it is refused, because a rank declared dead at the
        readopt deadline must not resurrect into a formed epoch)."""
        while True:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # freed
            try:
                conn.settimeout(30.0)
                msg = recv_msg(conn)
                conn.settimeout(None)
            except (OSError, ValueError):
                conn.close()
                continue
            cmd = msg.get("cmd") if msg else None
            if cmd == "readopt":
                self._handle_readopt(conn, msg)
                continue
            if not msg or cmd != "start":
                conn.close()
                continue
            with self._lock:
                if self._closing or self._error is not None:
                    conn.close()
                    continue
                self._joiners.append(conn)
                self._conns.append(conn)  # abort fan-out coverage
            self._request_regroup()

    def _handle_readopt(self, conn: socket.socket, msg: dict) -> None:
        try:
            rank = int(msg.get("rank", -1))
        except (TypeError, ValueError):
            rank = -1
        with self._lock:
            accept = (rank in self._readopt_pending and not self._closing
                      and self._error is None)
            if accept:
                self._readopt_pending.discard(rank)
                self._members[conn] = rank
                self._conns.append(conn)
                self._watched.add(conn)
                if msg.get("round") is not None:
                    self._progress_round[rank] = max(
                        self._progress_round.get(rank, 0),
                        int(msg["round"]))
            epoch = self._epoch
        if not accept:
            try:
                send_msg(conn, {"cmd": "abort",
                                "msg": "re-adoption refused (unknown rank, "
                                       "readopt deadline passed, or job "
                                       "over)"}, timeout=5.0)
            except OSError:
                pass
            conn.close()
            return
        from .telemetry import flight as _flight

        _flight.record("event", "tracker.readopt", rank=rank, epoch=epoch)
        try:
            send_msg(conn, {"cmd": "readopted", "epoch": epoch,
                            "failover": True}, timeout=30.0, peer=rank)
        except OSError:
            # the reply never arrived: ROLL BACK the membership — no
            # watcher exists yet, so a zombie member here would block
            # _maybe_complete_regroup forever — and re-open the rank so
            # the worker's backoff retry can re-adopt
            with self._lock:
                self._members.pop(conn, None)
                self._watched.discard(conn)
                if conn in self._conns:
                    self._conns.remove(conn)
                self._readopt_pending.add(rank)
            conn.close()
            return
        threading.Thread(target=self._watch_worker, args=(conn, rank),
                         daemon=True).start()
        self._journal_write(force=True)
        # the readopt does not JOIN the regroup — the worker's regroup()
        # does — but completion must be re-checked in case everyone else
        # already joined while this straggler was reconnecting
        self._maybe_complete_regroup()

    def _relay_worker_lost(self, rank: int, msg: str,
                           declared: bool = False) -> None:
        if not self.elastic:
            self._fan_abort(rank, msg, None)
            return
        with self._lock:
            conn = next((c for c, r in self._members.items() if r == rank),
                        None)
        if conn is not None:
            self._on_worker_death(conn, rank, msg, declared=declared)

    def _on_worker_death(self, conn: socket.socket, rank: int,
                         msg: str, declared: bool = False) -> None:
        """Elastic death handling (idempotent per connection): drop the
        member, flush the relay, and start a regroup among the survivors.
        With nobody left the job has failed — there is no one to carry the
        model forward."""
        with self._lock:
            if (conn not in self._members or self._closing
                    or self._error is not None):
                return
            del self._members[conn]
            self._regroup_joins.pop(conn, None)
            self.lost_workers += 1
            survivors = len(self._members)
            joiners = len(self._joiners)
            epoch_now = self._epoch
        # sever the channel: for an ACTUAL death this is a no-op (the
        # socket is already gone), but a DECLARED death — link deadline,
        # stall ladder — leaves a live wedged peer behind, and 'declared
        # dead' must recover identically to 'actually dead': its watcher
        # EOFs, its blocked collective surfaces, and it can never
        # half-participate in an epoch that no longer contains it
        if declared:
            # link-deadline declaration: the peer is likely alive behind
            # a half-open link — invite it back BEFORE severing (the
            # tracker->worker direction of an asymmetric cut usually
            # still works; best-effort either way).  Only an invited
            # worker rejoins, so stall-ladder declarations keep their
            # old fail-and-respawn semantics.
            try:
                send_msg(conn, {"cmd": "declared_dead", "rejoin": True},
                         timeout=5.0, peer=rank)
            except OSError:
                pass
        try:
            conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass
        from .elastic import instruments as _elastic_ins
        from .telemetry import flight as _flight

        _elastic_ins()[1].inc()
        _flight.record("event", "tracker.worker_lost", rank=rank, msg=msg)
        self._journal_write(force=True)
        warnings.warn(f"elastic: worker {rank} lost ({msg}); "
                      f"{survivors} survivor(s) regrouping", RuntimeWarning,
                      stacklevel=2)
        self._relay.invalidate(epoch_now)
        if survivors == 0 and joiners == 0:
            with self._lock:
                if self._error is None:
                    self._error = f"worker {rank}: {msg} (no survivors)"
            self._done.set()
            return
        if declared:
            # a DECLARED death (link deadline) severed a possibly-live
            # peer: hold the regroup open one grace window for its rejoin
            # handshake, so a healed half-open link restores the world
            # in the SAME regroup its loss triggered
            grace = _readmit_grace_s(self._relay.link_timeout)
            with self._lock:
                self._readmit_waiting += 1
                self._readmit_until = max(self._readmit_until,
                                          time.monotonic() + grace)
        self._request_regroup()

    def _request_regroup(self) -> None:
        """Announce a pending regroup to every live member (idempotent) and
        invalidate the relay so no gather can straddle the membership
        change; completion happens when the last member joins."""
        with self._lock:
            if self._closing or self._error is not None:
                return
            first = not self._regrouping
            if first:
                self._regrouping = True
                self._regroup_t0 = time.perf_counter()
            epoch_now = self._epoch
        if first:
            # invalidate BEFORE announcing: a member that hears the
            # announcement first would close its relay socket entering
            # regroup(), and with the flush flag not yet set the relay
            # would misread that live survivor as a mid-gather death
            self._relay.invalidate(epoch_now)
            with self._lock:
                if self._closing or self._error is not None:
                    return
                pending = list(self._members.items())
                next_epoch = self._epoch + 1
            for conn, peer in pending:
                try:
                    self._send_ctl(conn, {"cmd": "regroup_pending",
                                          "epoch": next_epoch},
                                   timeout=30.0, peer=peer)
                except OSError:
                    pass  # its watcher will report the death
        self._maybe_complete_regroup()

    def _handle_regroup_join(self, conn: socket.socket, round_: int) -> None:
        with self._lock:
            if conn not in self._members:
                return
            # a join can arrive before the tracker noticed the death (the
            # relay told the worker first): it opens the regroup
            if not self._regrouping:
                self._regrouping = True
                self._regroup_t0 = time.perf_counter()
            self._regroup_joins[conn] = int(round_)
            epoch_now = self._epoch
        self._relay.invalidate(epoch_now)
        self._maybe_complete_regroup()

    def _readmit_expire(self) -> None:
        """Grace timer: the declared-lost rank never came back — complete
        the regroup with whoever is here."""
        with self._lock:
            self._readmit_timer = False
        self._maybe_complete_regroup()

    def _count_readmission(self, outcome: str) -> None:
        """``xtb_net_readmissions_total{outcome}``: grace windows closed by
        a comeback (``readmitted``) vs timed out (``expired``)."""
        if self._readmit_ins is None:
            from .telemetry.registry import get_registry

            self._readmit_ins = get_registry().counter(
                "xtb_net_readmissions_total",
                "link-deadline readmission grace windows closed, by "
                "outcome (readmitted = the declared-lost rank rejoined "
                "the same regroup; expired = it never came back)",
                ("outcome",))
        self._readmit_ins.labels(outcome).inc()

    def _maybe_complete_regroup(self) -> None:
        """Form the next epoch once every live member has joined: compact
        rank assignment (survivors by previous rank, then parked joiners),
        relay re-formed, assignments sent — survivors on the persistent
        channel, joiners as their held start-handshake reply."""
        with self._lock:
            if (not self._regrouping or self._closing
                    or self._error is not None):
                return
            if self._readopt_pending:
                return  # a tracker-recovery re-adoption is still draining
            if set(self._regroup_joins) != set(self._members):
                return  # someone is still draining toward its boundary
            now = time.monotonic()
            if (self._readmit_waiting > len(self._joiners)
                    and now < self._readmit_until):
                # readmission grace: a rank declared lost by the link
                # deadline gets one bounded window to rejoin THIS regroup
                # (its rejoin 'start' handshake re-triggers completion);
                # forming without it would commit rounds at reduced
                # membership that a healed partition can never reconcile
                if not self._readmit_timer:
                    self._readmit_timer = True
                    t = threading.Timer(self._readmit_until - now + 0.05,
                                        self._readmit_expire)
                    t.daemon = True
                    t.start()
                return
            if self._readmit_waiting:
                self._count_readmission(
                    "readmitted"
                    if len(self._joiners) >= self._readmit_waiting
                    else "expired")
                self._readmit_waiting = 0
                self._readmit_until = 0.0
            survivors = sorted(self._members, key=self._members.get)
            old_ranks = dict(self._members)  # conn -> pre-regroup rank
            joiners = list(self._joiners)
            self._joiners = []
            ordered = survivors + joiners
            new_world = len(ordered)
            if new_world == 0:
                # everyone left while the regroup was pending (clean
                # finishes or deaths — both have their own error/success
                # accounting); there is simply no epoch to form
                self._regrouping = False
                self._regroup_joins = {}
                return
            self._epoch += 1
            epoch = self._epoch
            resume_round = max(self._regroup_joins.values(), default=0)
            self._regroup_joins = {}
            self._members = {conn: nr for nr, conn in enumerate(ordered)}
            # re-key the journal's per-rank resume rounds to the NEW
            # numbering: a dead or renumbered rank's stale entry must not
            # survive into the next recovery's journal (joiners start at
            # the epoch's resume round)
            self._progress_round = {
                nr: (self._progress_round.get(old_ranks[conn], 0)
                     if conn in old_ranks else resume_round)
                for nr, conn in enumerate(ordered)}
            self._regrouping = False
            self._watched.update(joiners)
            duration = time.perf_counter() - self._regroup_t0
            self._join_stage = {}
            # ranks were just re-numbered: stale liveness entries keyed by
            # the old ranks must not age anyone in the new epoch
            self._liveness = {}
            self._relay.regroup(new_world, epoch)
            epoch_state = None
            if self._journal is not None:
                self._journal_last = time.monotonic()
                epoch_state = self._journal_state()
            coll_port = self._relay.port
            failover = self._journal is not None
            announce = list(enumerate(ordered))
            # capture under the lock: a joiner's conn could die (and leave
            # _members) before the watcher threads below start
            joiner_ranks = [(conn, self._members[conn]) for conn in joiners]
        if epoch_state is not None:
            # durable-commit-first: the new epoch must hit the journal
            # BEFORE any worker is told about it — a tracker killed
            # between announce and journal would otherwise respawn
            # believing the OLD epoch while the workers run the new one.
            # Under _journal_io (NOT _lock — a slow disk must stall only
            # the journal, lockdep seam witness): a concurrent throttled
            # _journal_write holds _journal_io across its own capture+
            # append, so it cannot land a pre-epoch capture after this
            # entry and make replay resurrect the old epoch
            with self._journal_io:
                try:
                    self._journal.append(epoch_state)
                except OSError as e:
                    warnings.warn(
                        f"tracker journal write failed ({e}); a tracker "
                        "respawn may not recover this epoch",
                        RuntimeWarning, stacklevel=2)
        # announces OUTSIDE the state lock (XTB902): the journal commit
        # above still precedes every announce, and the per-connection tx
        # locks keep a concurrent regroup_pending/abort from shearing a
        # frame; a wedged peer stalls only its own socket
        for nr, conn in announce:
            try:
                self._send_ctl(conn, {"cmd": "regroup", "epoch": epoch,
                                      "rank": nr, "world": new_world,
                                      "round": resume_round,
                                      "coll_port": coll_port,
                                      "coordinator": "",
                                      # a parked JOINER's start handshake
                                      # is answered by this message: it
                                      # must learn failover/elastic are
                                      # armed here
                                      "failover": failover,
                                      "elastic": True},
                               timeout=30.0, peer=nr)
            except OSError:
                pass  # the death will be seen and regrouped again
        from .elastic import instruments as _elastic_ins
        from .telemetry import flight as _flight

        ins = _elastic_ins()
        ins[0].inc()
        ins[2].observe(duration)
        _flight.record("event", "tracker.regroup", epoch=epoch,
                       world=new_world, seconds=duration)
        for conn, jrank in joiner_ranks:
            threading.Thread(target=self._watch_worker,
                             args=(conn, jrank), daemon=True).start()

    # ------------------------------------------------------------- client API
    @property
    def rendezvous_complete(self) -> bool:
        """True once the initial cohort fully rendezvoused.  Elasticity
        starts HERE: a death before this point cannot be regrouped (the
        cohort does not exist yet) and must stay a fail-fast error."""
        with self._lock:
            return self._serve_done

    def worker_args(self) -> Dict[str, Union[str, int]]:
        """Env for workers (consumed by collective.init tracker mode: no
        pre-assigned rank — the tracker hands one out)."""
        return {
            "dmlc_tracker_uri": self.host_ip,
            "dmlc_tracker_port": self.port,
            "dmlc_nworker": self.n_workers,
        }

    def wait_for(self, timeout: int = 0) -> None:
        ok = self._done.wait(timeout or self.timeout or None)
        if not ok:
            raise TimeoutError("tracker wait_for timed out")
        if self._error is not None:
            raise RuntimeError(f"tracker: training failed — {self._error}")

    def free(self) -> None:
        with self._lock:
            # watcher EOFs from here on are OURS, not deaths
            self._closing = True
        self._relay.close()
        try:
            self._listener.close()
        except OSError:
            pass
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass
        self._done.set()


class TrackerClient:
    """Worker-side tracker connection: rendezvous + background abort watcher
    (the comm.cc:340-376 detached watcher thread role)."""

    def __init__(self, host: str, port: int, timeout: float = 120.0,
                 retries: int = 5, task_id: str = "",
                 handshake_timeout: float = OP_TIMEOUT) -> None:
        import os

        from .reliability import faults as _faults
        from .reliability.retry import RetriesExhausted, retry_call

        def _connect() -> socket.socket:
            # seam: kinds 'exception' (with times=N, a connect that fails N
            # times then succeeds — retried like a real refusal) and 'delay'
            _faults.maybe_inject("tracker.connect")
            return socket.create_connection((host, int(port)),
                                           timeout=timeout)

        try:
            # jittered exponential backoff (comm.h:23 kRetry role): workers
            # racing the tracker's start() — or a tracker restarting — win
            # eventually, de-synchronized by the pid-seeded jitter
            self._sock = retry_call(
                _connect, op="tracker.connect",
                retries=max(retries, 1) - 1, base=0.25, max_delay=10.0,
                seed=os.getpid(),
                retry_on=(OSError, _faults.FaultInjected))
        except RetriesExhausted as e:
            raise ConnectionError(
                f"cannot reach tracker {host}:{port}: {e.__cause__}") from e
        # the whole rendezvous handshake is bounded: a tracker that accepts
        # and then stalls becomes a ConnectionError here, not a hang
        self._sock.settimeout(handshake_timeout)
        send_msg(self._sock, {"cmd": "start", "host": socket.gethostname(),
                              "task_id": task_id})
        try:
            reply = recv_msg(self._sock)
        except OSError as e:
            raise ConnectionError(
                f"tracker handshake failed or timed out: {e}") from e
        if not reply or "rank" not in reply:
            raise ConnectionError("tracker rejected the start handshake")
        self.rank = int(reply["rank"])
        self.world = int(reply["world"])
        self.coll_port = reply.get("coll_port")  # socket-relay collectives
        # elastic: a replacement worker's handshake is answered by the
        # regroup itself, which carries the epoch (recovery reloads the
        # newest checkpoint rather than trusting a reported round)
        self.epoch = int(reply.get("epoch", 0))
        # failover: the tracker journals its state — a dropped channel is
        # a coordinator respawn to reconnect to, not (necessarily) the end
        self.failover = bool(reply.get("failover", False))
        # elastic: a severed channel may be a DECLARED death (link
        # deadline) of this very-much-alive process — worth one rejoin
        # attempt before giving up the job, but only when the tracker's
        # pre-sever invitation said so
        self.elastic = bool(reply.get("elastic", False))
        self._rejoin_invited = False
        self._host = host
        self._port = int(port)
        self._closed = False
        self._channel_dead = False
        self._coll: Optional[socket.socket] = None
        self._coll_host = host
        self._coll_seq = 0
        self._coll_interrupted = False  # set by the collective watchdog
        self._coll_lock = threading.Lock()
        # serialization lock (_XTB_SERIAL_LOCKS): held across wire I/O by
        # contract, so the runtime witness must not flag the seam crossing
        _lockdep.mark_serial(self._coll_lock)
        self._state_lock = threading.Lock()
        self._connected = threading.Event()      # channel is usable
        self._connected.set()
        self._regroup_flag = threading.Event()   # regroup_pending received
        self._regroup_ready = threading.Event()  # assignment received
        self._regroup_info: Optional[dict] = None
        self.op_timeout = handshake_timeout
        if reply.get("coordinator") is None:
            # rank 0: host the jax coordinator — allocate a port on THIS
            # machine and report it back (bind-then-close is a small TOCTOU
            # window; jax.distributed offers no way to hand over a bound
            # socket, so the race is accepted and retried at a higher level)
            my_ip = get_host_ip()
            with socket.socket() as s:
                s.bind((my_ip, 0))
                self.coordinator = f"{my_ip}:{s.getsockname()[1]}"
            send_msg(self._sock, {"cmd": "coordinator",
                                  "addr": self.coordinator},
                     peer=self.rank)
        else:
            self.coordinator = str(reply["coordinator"])
        # handshake done: the persistent connection is now the abort channel
        # and legitimately blocks forever in the watcher
        self._sock.settimeout(None)
        # seam: 'drop_connection' severs the error channel right after
        # rendezvous — the tracker sees EOF and treats this worker as dead
        spec = _faults.maybe_inject("tracker.connected", rank=self.rank)
        if spec is not None and spec.kind == "drop_connection":
            try:
                self._sock.close()
            except OSError:
                pass
        self._watcher = threading.Thread(target=self._watch, daemon=True)
        self._watcher.start()

    def _watch(self) -> None:
        while True:
            try:
                msg = recv_msg(self._sock, peer=self.rank)
            except socket.timeout:
                # a concurrent TIMED send (ship_telemetry / signal_error
                # both bound their sends) toggles the shared socket's
                # timeout; a watcher recv entered in that window inherits
                # it and expires on an idle channel.  That is not a death
                # — retry.  (Mid-frame expiry would desync framing, but
                # the watcher sits at a frame boundary and abort/regroup
                # frames arrive as single segments.)
                continue
            except OSError:
                msg = None
            if msg is None:
                # channel down.  Clean shutdown or a non-failover tracker:
                # this watcher's job is over (the old semantics).  With
                # failover armed the coordinator is respawning — reconnect
                # with backoff and re-adopt into the journaled epoch.
                if self._closed or not self.failover:
                    if not self._closed:
                        # elastic: an invited sever is a DECLARED death
                        # (link deadline) of this live process — the
                        # tracker holds the regroup open a grace window
                        # for exactly this comeback
                        with self._state_lock:
                            invited = self._rejoin_invited
                        if self.elastic and invited and self._rejoin():
                            with self._state_lock:
                                self._rejoin_invited = False
                            continue
                        # a regroup entered (or about to be entered) on a
                        # DEAD channel would wait out its full timeout for
                        # an assignment that can never arrive: fail it now
                        self._channel_lost()
                    return
                if not self._reconnect():
                    self._channel_lost()
                    return
                continue
            if msg.get("cmd") == "declared_dead":
                # the coordinator is about to sever us over a link-
                # deadline declaration: the EOF that follows is an
                # invitation to rejoin, not the end of the job
                with self._state_lock:
                    self._rejoin_invited = bool(msg.get("rejoin"))
                continue
            if msg.get("cmd") == "stackdump":
                # the tracker's stall watchdog wants to see this process's
                # threads: the watcher can answer even when the main
                # thread is wedged — exactly the case being diagnosed
                try:
                    from .telemetry import flight

                    flight.dump_stacks()
                    flight.dump()
                except Exception:
                    pass
                continue
            if msg.get("cmd") == "abort":
                import os
                import sys

                print(f"[rank {self.rank}] aborting: peer failure — "
                      f"{msg.get('msg', '')}", file=sys.stderr, flush=True)
                try:
                    # os._exit skips atexit: flush the flight ring so the
                    # aborted peer's postmortem shows ITS last moments too
                    # — plus an all-thread stack dump, so "what was this
                    # process doing when it was told to die" is on disk
                    from .telemetry import flight

                    flight.record("fault", "tracker.abort",
                                  msg=msg.get("msg", ""))
                    flight.dump_stacks()
                    flight.dump()
                except Exception:
                    pass
                os._exit(255)  # reference: std::exit(-1) in the watcher
            if msg.get("cmd") == "regroup_pending":
                # picked up by the training loop at its round boundary
                # (and by any collective about to enter the relay)
                self._regroup_flag.set()
                continue
            if msg.get("cmd") == "regroup":
                with self._state_lock:
                    self._regroup_info = msg
                self._regroup_flag.set()
                self._regroup_ready.set()
                continue

    def _reconnect(self) -> bool:
        """Re-adopt into a respawned tracker: jittered-backoff reconnect,
        ``readopt`` handshake carrying this worker's rank/epoch/last
        round, and a pending-regroup flag so the training loop drains to
        its round boundary and joins the re-adoption epoch.  Returns
        False when the coordinator never came back (the job is over; the
        callers fail loudly through the normal paths)."""
        import os

        from .reliability import watchdog as _watchdog
        from .reliability.retry import RetriesExhausted, retry_call
        from .telemetry import flight

        self._connected.clear()
        # membership is about to change (the re-adoption forms a new
        # epoch): collectives must drain into regroup, not retry a relay
        # that died with the old tracker
        self._regroup_flag.set()
        self.interrupt_collective()
        try:
            self._sock.close()
        except OSError:
            pass
        marks = _watchdog.markers().get("train.round") or {}
        flight.record("event", "tracker.reconnect", rank=self.rank,
                      epoch=self.epoch)

        def _dial() -> socket.socket:
            s = socket.create_connection((self._host, self._port),
                                         timeout=30.0)
            try:
                s.settimeout(30.0)
                send_msg(s, {"cmd": "readopt", "rank": self.rank,
                             "epoch": self.epoch,
                             "round": marks.get("round")},
                         peer=self.rank)
                reply = recv_msg(s, peer=self.rank)
                if not reply or reply.get("cmd") != "readopted":
                    raise ConnectionError(
                        f"tracker refused re-adoption: {reply!r}")
            except BaseException:
                s.close()
                raise
            return s

        try:
            retries = int(os.environ.get("XGBOOST_TPU_READOPT_RETRIES",
                                         "15"))
        except ValueError:
            retries = 15
        try:
            s = retry_call(_dial, op="tracker.readopt", retries=retries,
                           base=0.25, max_delay=2.0, seed=self.rank,
                           retry_on=(OSError, ValueError))
        except RetriesExhausted as e:
            flight.record("fault", "tracker.readopt_failed",
                          rank=self.rank, error=str(e))
            return False
        s.settimeout(None)
        with self._state_lock:
            self._sock = s
        self._connected.set()
        flight.record("event", "tracker.readopted", rank=self.rank,
                      epoch=self.epoch)
        return True

    def _rejoin(self) -> bool:
        """Severed by the coordinator while this process is alive — the
        signature of a DECLARED death (per-link deadline): the tracker
        cut the channel expecting either a corpse or a comeback.  One
        bounded attempt at the comeback: re-run the ``start`` handshake
        as a replacement joiner and adopt the regroup assignment it is
        answered with (the tracker holds that regroup open for a
        readmission grace window, so a healed half-open link restores
        the original world).  Returns False when the tracker is really
        gone — the caller fails loud through :meth:`_channel_lost`."""
        from .telemetry import flight

        self._connected.clear()
        # membership is changing: the blocked collective must drain into
        # RegroupRequired, not retry a relay epoch we are no longer in
        self._regroup_flag.set()
        self.interrupt_collective()
        try:
            self._sock.close()
        except OSError:
            pass
        flight.record("event", "tracker.rejoin", rank=self.rank,
                      epoch=self.epoch)
        try:
            s = socket.create_connection((self._host, self._port),
                                         timeout=10.0)
        except OSError:
            return False
        try:
            # the reply IS the regroup assignment (a parked joiner's
            # handshake is answered at absorption) — bounded: a tracker
            # that parks us forever surfaces as a timeout, not a hang
            s.settimeout(30.0)
            send_msg(s, {"cmd": "start", "host": socket.gethostname(),
                         "task_id": f"rejoin-{self.rank}"})
            reply = recv_msg(s)
        except (OSError, ValueError):
            try:
                s.close()
            except OSError:
                pass
            return False
        if not reply or "rank" not in reply:
            try:
                s.close()
            except OSError:
                pass
            return False
        s.settimeout(None)
        with self._state_lock:
            self._regroup_info = reply
            self._sock = s
        self._regroup_ready.set()
        self._connected.set()
        flight.record("event", "tracker.rejoined",
                      rank=reply.get("rank"), epoch=reply.get("epoch"))
        return True

    def _channel_lost(self) -> None:
        """Tracker channel permanently gone (non-failover EOF, or every
        re-adoption attempt failed): wake anything waiting on a regroup
        assignment — with ``_regroup_info`` left None, :meth:`regroup`
        raises instead of sleeping out its timeout on a dead socket."""
        with self._state_lock:
            self._regroup_info = None
            self._channel_dead = True
        self._connected.set()  # a send on the dead socket fails FAST
        self._regroup_ready.set()
        self.interrupt_collective()

    def interrupt_collective(self) -> None:
        """Poke a thread blocked in :meth:`coll_allgather` awake by
        closing the relay socket (the blocked recv surfaces OSError →
        ``RegroupRequired``).  Called by the collective-wait watchdog at
        its stall stage and by :meth:`_reconnect` — both from OTHER
        threads, so no lock: the blocked collective holds it."""
        with self._state_lock:
            self._coll_interrupted = True
        c = self._coll
        if c is not None:
            # shutdown() wakes the blocked recv reliably; a bare close()
            # can leave the other thread blocked on the dead fd forever
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    @property
    def regroup_pending(self) -> bool:
        """True once the tracker announced a membership change this worker
        has not yet regrouped into."""
        return self._regroup_flag.is_set()

    def regroup(self, completed_round: int,
                timeout: Optional[float] = None) -> dict:
        """Round-boundary regroup: drop the stale relay connection, join
        the barrier on the tracker, and adopt the new ``(rank, world)``
        assignment for the next epoch.  Returns the assignment message
        (``round`` is the highest completed round any survivor reported —
        recovery reloads the newest checkpoint at or below it)."""
        from .reliability import faults as _faults

        # seam: 'delay' (slow joiner), 'exception' (regroup machinery
        # fault), 'kill' (a worker dying DURING the regroup — the tracker
        # must detect it and complete with the remaining members)
        _faults.maybe_inject("tracker.regroup", rank=self.rank)
        with self._coll_lock:
            if self._coll is not None:
                try:
                    self._coll.close()
                except OSError:
                    pass
            self._coll = None
            self._coll_seq = 0
        with self._state_lock:
            if self._channel_dead:
                raise RuntimeError(
                    "tracker channel lost: cannot regroup (a join would "
                    "wait on a dead socket)")
            self._coll_interrupted = False  # the new epoch starts clean
        self._regroup_ready.clear()
        wait_s = timeout or self.op_timeout
        early = False
        for attempt in range(3):
            # failover: a regroup can be entered WHILE the watcher is
            # still re-adopting into a respawned tracker — wait for the
            # channel, and retry the join if the send raced a reconnection
            # (the watcher may not have noticed the dead socket yet)
            if not self._connected.wait(timeout=wait_s):
                raise RuntimeError(
                    "tracker unreachable during elastic regroup "
                    "(re-adoption never completed)")
            with self._state_lock:
                early = self._regroup_info is not None
            if early:
                # a rejoin handshake (declared-dead comeback) was already
                # answered with the assignment itself: no join to send —
                # and the ready event it set may predate the clear above
                break
            try:
                send_msg(self._sock, {"cmd": "regroup_join",
                                      "round": int(completed_round)},
                         timeout=30.0, peer=self.rank)
                break
            except OSError as e:
                if attempt >= 2 or not self.failover:
                    raise RuntimeError(
                        f"tracker unreachable during elastic regroup: {e}"
                    ) from e
                time.sleep(0.5)  # let the watcher notice and reconnect
        if not early and not self._regroup_ready.wait(wait_s):
            raise RuntimeError(
                "elastic regroup timed out waiting for the tracker "
                "assignment")
        with self._state_lock:
            if self._channel_dead or self._regroup_info is None:
                raise RuntimeError(
                    "tracker channel lost during elastic regroup: no "
                    "assignment can arrive — failing loud instead of "
                    "waiting out the timeout")
            info = self._regroup_info
            self._regroup_info = None
            self.rank = int(info["rank"])
            self.world = int(info["world"])
            self.epoch = int(info["epoch"])
            if info.get("coll_port") is not None:
                self.coll_port = info["coll_port"]
            if info.get("failover") is not None:
                # a replacement worker's handshake was answered by this
                # very message — adopt the tracker's failover capability
                self.failover = bool(info["failover"])
        self._regroup_ready.clear()
        self._regroup_flag.clear()
        return dict(info)

    # --------------------------------------------------- relay collectives
    def _coll_sock(self) -> socket.socket:
        if self._coll is None:
            if self.coll_port is None:
                raise RuntimeError("tracker offers no collective relay")
            from .reliability.retry import retry_call

            self._coll = retry_call(
                lambda: socket.create_connection(
                    (self._coll_host, int(self.coll_port)), timeout=60.0),
                op="tracker.coll_connect", retries=4, base=0.25,
                seed=self.rank, retry_on=(OSError,))
            send_msg(self._coll, {"cmd": "coll_join", "rank": self.rank,
                                  "epoch": self.epoch},
                     timeout=30.0, peer=self.rank)
        return self._coll

    def _await_regroup_verdict(self, budget_s: float = 2.0) -> bool:
        """A severed relay connection in elastic mode usually means the
        tracker just declared this rank (link deadline) and the control
        channel's verdict — a ``declared_dead`` invitation plus EOF, or a
        regroup broadcast — is milliseconds behind on the watcher thread.
        Poll briefly for it so the collective surfaces RegroupRequired
        (recoverable) instead of a hard I/O error (fatal).  A rank the
        tracker really has abandoned burns the budget and fails exactly
        as before."""
        deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline:
            if self._regroup_flag.is_set() or self._coll_interrupted:
                return True
            time.sleep(0.05)
        return False

    def coll_allgather(self, arr) -> "object":
        """Rank-ordered allgather over the tracker's socket relay:
        (world, *arr.shape).  The CPU-backend fallback for XLA multiprocess
        collectives (CollRelay docstring has the why + failure model)."""
        import numpy as np

        arr = np.ascontiguousarray(arr)
        payload = arr.tobytes()
        with self._coll_lock:
            if self._regroup_flag.is_set():
                # membership already changed: entering the relay would only
                # contribute a buffer the regroup is about to flush
                raise RegroupRequired("elastic regroup pending")
            s = self._coll_sock()
            seq = self._coll_seq
            self._coll_seq += 1
            try:
                send_msg(s, {"cmd": "coll", "seq": seq,
                             "nbytes": len(payload),
                             "crc": _crc32(payload)},
                         timeout=self.op_timeout, peer=self.rank,
                         trailing=payload)
                hdr = recv_msg(s, timeout=self.op_timeout,
                               peer=self.rank)
                if hdr and hdr.get("cmd") == "coll_regroup":
                    raise RegroupRequired(
                        "collective membership changed mid-operation")
                if not hdr or hdr.get("cmd") != "coll_result":
                    if hdr is None and (self._coll_interrupted
                                        or self._regroup_flag.is_set()
                                        or self.failover
                                        or (self.elastic
                                            and self._await_regroup_verdict())):
                        # a shutdown() poke (watchdog stall stage /
                        # failover reconnect) surfaces as clean EOF here,
                        # not OSError: same recovery — drain into regroup
                        raise RegroupRequired(
                            "collective interrupted; regrouping")
                    raise RuntimeError(
                        "collective relay failed: "
                        f"{(hdr or {}).get('msg', 'connection lost')}")
                buf = _recv_exact(s, int(hdr["nbytes"]),
                                  timeout=self.op_timeout)
                if (hdr.get("crc") is not None
                        and _crc32(buf) != hdr["crc"]):
                    # a damaged gather result must never reach the
                    # histogram fold: fail the connection, not the math
                    from .reliability import integrity as _integrity

                    _integrity.corrupt_detected("tracker")
                    raise ConnectionError(
                        f"relay gather seq {seq} CRC mismatch: corrupted "
                        "payload — dropping the relay connection")
            except OSError as e:
                if (self._regroup_flag.is_set() or self._coll_interrupted
                        or (self.elastic
                            and self._await_regroup_verdict())):
                    # elastic regroup pending, or the collective-wait
                    # watchdog severed the relay at its stall stage: both
                    # recover through the regroup path
                    raise RegroupRequired(
                        "collective interrupted by elastic regroup") from e
                if self.failover:
                    # the relay died with the tracker: the respawned
                    # coordinator re-adopts us and the job regroups —
                    # a dead relay is a membership change, not a job loss
                    raise RegroupRequired(
                        "collective relay lost; tracker failover in "
                        "progress") from e
                raise RuntimeError(
                    f"collective relay I/O failed (peer/tracker lost?): {e}"
                ) from e
        return np.frombuffer(buf, arr.dtype).reshape(
            (self.world,) + arr.shape).copy()

    def ship_telemetry(self, payload: dict) -> bool:
        """Send a registry-snapshot + flight-ring payload
        (``telemetry.distributed.snapshot_payload()``) to the tracker on
        the persistent channel.  Best-effort and bounded: a wedged or
        gone tracker costs one timeout, never the training run."""
        msg = {"cmd": "telemetry",
               "snapshot": payload.get("snapshot"),
               "flight": payload.get("flight"),
               "progress": payload.get("progress"),
               "pid": payload.get("pid", 0)}
        try:
            send_msg(self._sock, msg, timeout=30.0, peer=self.rank)
            return True
        except (OSError, TypeError, ValueError):
            return False

    def signal_error(self, msg: str) -> None:
        # bounded: a dying worker must not block on a wedged tracker
        try:
            send_msg(self._sock, {"cmd": "error", "msg": msg}, timeout=30.0,
                     peer=self.rank)
        except OSError:
            pass

    def shutdown(self) -> None:
        with self._state_lock:
            # the watcher must read the coming EOF as OUR close, not a
            # tracker death to re-adopt from
            self._closed = True
        with self._coll_lock:
            if self._coll is not None:
                try:
                    self._coll.close()
                except OSError:
                    pass
                self._coll = None
        try:
            send_msg(self._sock, {"cmd": "shutdown"}, timeout=30.0,
                     peer=self.rank)
            self._sock.close()
        except OSError:
            pass
