"""Tracker shim (reference: python-package/xgboost/tracker.py RabitTracker,
src/collective/tracker.cc).

The reference tracker is a socket rendezvous server assigning (rank, world,
ring neighbors).  Under JAX that role belongs to the jax.distributed
coordinator, so this class only carries the coordinator address/port in the
reference's env-var vocabulary — existing dask-style launch scripts keep
working, with the coordinator service doing the actual bootstrap.
"""
from __future__ import annotations

import socket
from typing import Dict, Union


def get_host_ip(host_ip: str = "auto") -> str:
    if host_ip and host_ip != "auto":
        return host_ip
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
    except Exception:
        ip = "127.0.0.1"
    finally:
        s.close()
    return ip


class RabitTracker:
    """Coordinator-address holder with the reference's surface
    (tracker.py:17): worker_args(), start(), wait_for()."""

    def __init__(self, n_workers: int, host_ip: str = "auto", port: int = 0,
                 sortby: str = "host", timeout: int = 0) -> None:
        self.n_workers = n_workers
        self.host_ip = get_host_ip(host_ip)
        if port == 0:
            with socket.socket() as s:
                s.bind((self.host_ip, 0))
                port = s.getsockname()[1]
        self.port = port
        self._started = False

    def start(self) -> None:
        # jax.distributed's coordinator is started lazily by process 0 inside
        # jax.distributed.initialize; nothing to spawn here
        self._started = True

    def worker_args(self) -> Dict[str, Union[str, int]]:
        """Env passed to workers (consumed by collective.init)."""
        return {
            "dmlc_tracker_uri": self.host_ip,
            "dmlc_tracker_port": self.port,
            "dmlc_nworker": self.n_workers,
        }

    def wait_for(self, timeout: int = 0) -> None:
        self._started = False

    def free(self) -> None:
        self._started = False
