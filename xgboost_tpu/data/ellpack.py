"""EllpackPage: the device-resident binned feature matrix.

TPU-native analogue of the reference's EllpackPage / GHistIndexMatrix
(src/data/ellpack_page.cuh:26 EllpackAccessorImpl, src/data/gradient_index.h:43).
The reference stores bit-packed global bin indices with a fixed row stride; on
TPU we store a dense (R_pad, F) matrix of *feature-local* bin indices in the
smallest integer dtype that fits, padded so every feature has the same bin
width B — regular shapes are what XLA tiles well, and the histogram kernel
builds its one-hot from local indices directly.

Missing values use the sentinel bin ``B`` (one past the padded width): its
one-hot row is all-zero, so missing rows simply don't contribute to histograms,
matching the reference where missing entries are absent from Ellpack and the
split evaluator routes them via the learned default direction.

Row padding: rows are padded to a multiple of ``row_align`` with sentinel bins
and position -1 so chunked kernels see static shapes; padded rows carry zero
gradients and never match a node mask.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from .quantile import HistogramCuts

MISSING_SENTINEL = "B"  # documented: sentinel == padded width B


def _bin_dtype(n_symbols: int):
    import jax.numpy as jnp

    if n_symbols <= 255:
        return jnp.uint8
    if n_symbols <= 32766:
        return jnp.int16
    return jnp.int32


@dataclasses.dataclass
class EllpackPage:
    """Device binned matrix + cut metadata.

    bins      : (R_pad, F) int — local bin index in [0, n_bins(f)), sentinel=B.
    cuts_pad  : (F, B) f32 — padded cut upper bounds (+inf pads).
    n_bins    : (F,) int32 — valid bin count per feature.
    n_rows    : logical row count (R_pad >= n_rows).
    """

    bins: "object"
    cuts_pad: "object"
    n_bins: "object"
    n_rows: int
    cuts: HistogramCuts

    @property
    def n_features(self) -> int:
        return int(self.bins.shape[1])

    @property
    def n_padded(self) -> int:
        return int(self.bins.shape[0])

    @property
    def bin_width(self) -> int:
        return int(self.cuts_pad.shape[1])


def build_ellpack(
    X,
    cuts: HistogramCuts,
    row_align: int = 1024,
    device=None,
) -> EllpackPage:
    """Bin a dense (R, F) float matrix against ``cuts`` on device.

    bin = searchsorted(cuts_f, v, side='right') == count of cuts <= v, matching
    the reference's upper_bound search (src/common/hist_util.h SearchBin);
    values past the last cut are clamped into the top bin, NaN -> sentinel B.
    """
    import jax
    import jax.numpy as jnp

    R, F = X.shape
    assert F == cuts.n_features
    B = cuts.max_n_bins
    R_pad = ((R + row_align - 1) // row_align) * row_align
    cuts_pad = jnp.asarray(cuts.padded(B))  # (F, B), +inf padded
    n_bins = jnp.asarray(cuts.n_bins_array())  # (F,)
    dtype = _bin_dtype(B + 1)

    # native ingestion fast path (CPU backend): the threaded row-sharded
    # binning kernel streams X once, row-major, and writes the page
    # sequentially — bitwise-equal to the XLA searchsorted formulation
    # below (upper_bound + top-bin clamp + NaN sentinel), pinned by
    # tests/test_native_threads.py::test_ellpack_native_bin_parity
    if jax.default_backend() == "cpu":
        from ..utils import native as _native

        binned = _native.ellpack_bin_native(
            np.asarray(X, np.float32), cuts.cut_values, cuts.cut_ptrs, B,
            np.dtype(dtype))
        if binned is not None:
            bins = jnp.asarray(binned)
            if R_pad != R:
                pad = jnp.full((R_pad - R, F), B, dtype=dtype)
                bins = jnp.concatenate([bins, pad], axis=0)
            return EllpackPage(bins=bins, cuts_pad=cuts_pad, n_bins=n_bins,
                               n_rows=R, cuts=cuts)

    Xd = jnp.asarray(X, dtype=jnp.float32)

    @jax.jit
    def _bin(Xd):
        # vectorized per-feature searchsorted: count cuts <= v
        def one_feature(col, fcuts, nb):
            b = jnp.searchsorted(fcuts, col, side="right").astype(jnp.int32)
            b = jnp.minimum(b, nb - 1)  # clamp overflow into top bin
            b = jnp.where(jnp.isnan(col), B, b)
            return b

        bins = jax.vmap(one_feature, in_axes=(1, 0, 0), out_axes=1)(Xd, cuts_pad, n_bins)
        return bins.astype(dtype)

    bins = _bin(Xd)
    if R_pad != R:
        pad = jnp.full((R_pad - R, F), B, dtype=dtype)
        bins = jnp.concatenate([bins, pad], axis=0)
    return EllpackPage(bins=bins, cuts_pad=cuts_pad, n_bins=n_bins, n_rows=R, cuts=cuts)


def build_ellpack_csr(indptr, indices, values, n_features: int, cuts: HistogramCuts,
                      row_align: int = 1024) -> EllpackPage:
    """Bin CSR input: implicit zeros are missing (sentinel), stored values binned.

    Host-side scatter into the dense local-bin layout; the result ships to
    device once.  (Reference: GHistIndexMatrix::PushBatch over SparsePage rows.)
    """
    import jax.numpy as jnp

    R = len(indptr) - 1
    B = cuts.max_n_bins
    dense = np.full((R, n_features), np.int32(B), dtype=np.int32)
    ptrs = cuts.cut_ptrs
    vals_all = cuts.cut_values
    row_of = np.repeat(np.arange(R), np.diff(indptr))
    v = values.astype(np.float32)
    ok = ~np.isnan(v)
    f = indices[ok]
    r = row_of[ok]
    vv = v[ok]
    # per-entry searchsorted within feature segment
    b = np.empty(len(vv), dtype=np.int32)
    for feat in np.unique(f):
        m = f == feat
        seg = vals_all[ptrs[feat] : ptrs[feat + 1]]
        bb = np.searchsorted(seg, vv[m], side="right")
        b[m] = np.minimum(bb, len(seg) - 1)
    dense[r, f] = b
    R_pad = ((R + row_align - 1) // row_align) * row_align
    if R_pad != R:
        dense = np.concatenate([dense, np.full((R_pad - R, n_features), B, np.int32)], axis=0)
    dtype = _bin_dtype(B + 1)
    return EllpackPage(
        bins=jnp.asarray(dense, dtype=dtype),
        cuts_pad=jnp.asarray(cuts.padded(B)),
        n_bins=jnp.asarray(cuts.n_bins_array()),
        n_rows=R,
        cuts=cuts,
    )
