"""DMatrix family: user-facing data containers.

TPU-native re-design of the reference DMatrix (include/xgboost/data.h:549,
MetaInfo data.h:65, SimpleDMatrix src/data/simple_dmatrix.h:20, QuantileDMatrix
src/data/iterative_dmatrix.h:34).  The reference keeps CSR pages and converts
to Ellpack/GHist lazily per tree method; here the canonical compute format IS
the Ellpack page (a dense jax.Array of bin indices), built lazily on first
training touch or eagerly by QuantileDMatrix.  ``ref=`` sharing of cuts between
train and validation mirrors GetCutsFromRef (src/data/quantile_dmatrix.cc:19).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

from .ellpack import EllpackPage, build_ellpack, build_ellpack_csr
from .quantile import HistogramCuts, sketch_csr, sketch_dense


@dataclasses.dataclass
class MetaInfo:
    """Labels and auxiliary per-row/per-feature metadata (reference: data.h:65-116)."""

    num_row: int = 0
    num_col: int = 0
    label: Optional[np.ndarray] = None
    weight: Optional[np.ndarray] = None
    base_margin: Optional[np.ndarray] = None
    group_ptr: Optional[np.ndarray] = None  # ranking query groups (CSR ptr)
    label_lower_bound: Optional[np.ndarray] = None  # survival
    label_upper_bound: Optional[np.ndarray] = None
    feature_names: Optional[List[str]] = None
    feature_types: Optional[List[str]] = None
    feature_weights: Optional[np.ndarray] = None

    def validate(self) -> None:
        for name in ("label", "weight", "base_margin"):
            arr = getattr(self, name)
            if arr is not None and arr.shape[0] != self.num_row:
                raise ValueError(
                    f"{name} has {arr.shape[0]} rows, expected {self.num_row}"
                )
        if self.group_ptr is not None and self.group_ptr[-1] != self.num_row:
            raise ValueError("group sizes must sum to num_row")


def _load_uri(uri: str):
    """DMatrix::Load (data.h:610): 'path', 'path?format=libsvm|csv'.

    Parsing runs in the native C++ library (native/xtb_native.cc) with a
    Python fallback — the analogue of dmlc-core's text parsers."""
    from ..utils.native import parse_csv, parse_libsvm

    path, _, query = uri.partition("?")
    fmt = None
    for part in query.split("&"):
        if part.startswith("format="):
            fmt = part.split("=", 1)[1]
    if fmt is None:
        fmt = "csv" if path.endswith(".csv") else "libsvm"
    if fmt == "csv":
        arr = parse_csv(path)
        return ("dense", arr), None, None, None, None
    indptr, indices, values, labels, qids, n_col = parse_libsvm(path)
    return (("csr", (indptr, indices, values, (len(indptr) - 1, n_col))),
            None, None, labels, qids)


def _is_jax_array(data: Any) -> bool:
    return type(data).__module__.split(".")[0] in ("jax", "jaxlib") and hasattr(
        data, "devices"
    )


def _normalize_dense(arr, missing: float, xp, feature_types=None):
    """1-D promotion + custom-missing -> NaN, shared by the host (xp=numpy)
    and device (xp=jax.numpy) ingest paths so their semantics cannot drift.

    ``feature_types``: when given (columnar adapters), the sentinel applies
    to NUMERIC columns only — categorical columns already hold dictionary
    CODES whose values are unrelated to the user's sentinel (a sentinel of
    0.0 must not wipe out category 0)."""
    if arr.ndim == 1:
        arr = arr[:, None]
    missing_is_nan = missing is None or (
        isinstance(missing, (float, np.floating)) and np.isnan(missing))
    if not missing_is_nan:
        hit = arr == missing
        if feature_types is not None:
            num_col = np.asarray([t != "c" for t in feature_types], bool)
            hit = hit & num_col[None, :]
        arr = xp.where(hit, xp.nan, arr)
    return arr


def categories_by_name(cat_categories: Optional[dict],
                       feature_names: Optional[Sequence[str]],
                       ) -> Optional[Dict[str, list]]:
    """Render a ``{feature index -> category values}`` mapping with feature
    NAMES as keys (index string when unnamed) — the single implementation
    behind every ``get_categories`` surface (DMatrix, Booster,
    InferenceSnapshot; reference: src/data/cat_container.h)."""
    if not cat_categories:
        return None
    names = feature_names
    return {
        (names[fi] if names and fi < len(names) else str(fi)): list(vals)
        for fi, vals in sorted(cat_categories.items())
    }


def recode_dense(X: np.ndarray, train_cats: Optional[dict],
                 data_cats: Optional[dict]) -> np.ndarray:
    """Remap categorical codes in a dense matrix from ``data_cats`` (the
    frame the matrix was built from) onto ``train_cats`` (the TRAINING
    frame's category->code mapping; reference: encoder/ordinal.h Recode).
    Returns ``X`` untouched when the orderings already agree; raises on a
    category never seen in training.  Shared by Booster prediction and the
    serving snapshot so both route codes through the same split sets."""
    if not train_cats or not data_cats or train_cats == {
            int(k): list(v) for k, v in data_cats.items()}:
        return X
    X = np.array(X, copy=True)
    for f, train_vals in train_cats.items():
        new_vals = data_cats.get(f)
        if new_vals is None or list(new_vals) == list(train_vals):
            continue
        lookup = {v: i for i, v in enumerate(train_vals)}
        codes = X[:, f]
        remapped = np.full_like(codes, np.nan)
        for new_code, v in enumerate(new_vals):
            hit = codes == new_code
            if v in lookup:
                remapped[hit] = lookup[v]
            elif hit.any():
                raise ValueError(
                    f"feature {f} has category {v!r} not seen in "
                    "training (encoder recode)")
        X[:, f] = remapped
    return X


def _to_numpy_2d(data: Any, missing: float = np.nan):
    """Dispatch user input -> (dense ndarray | csr triple, feature names/types).

    Mirrors the adapter dispatch of the reference (src/data/adapter.h,
    python-package/xgboost/data.py): numpy, pandas, scipy CSR/CSC, list.
    Device arrays never pass through here — DMatrix keeps jax.Array input
    on device (the CudfAdapter/CupyAdapter role, src/data/device_adapter.cuh).
    """
    feature_names = None
    feature_types = None
    # torch / other dlpack producers: zero-copy host view (reference:
    # src/data/array_interface.h dlpack ingestion).  Zero-copy contract:
    # the caller must not mutate the buffer before training first touches
    # this DMatrix (binning is lazy for plain DMatrix).
    if not isinstance(data, np.ndarray) and hasattr(data, "__dlpack__"):
        try:
            data = np.from_dlpack(data)
        except (TypeError, RuntimeError, BufferError):
            pass  # fall through to np.asarray
    # pyarrow Table / RecordBatch (columnar adapter; reference:
    # ColumnarAdapter src/data/adapter.h:437 + data.py _from_arrow)
    from .arrow import arrow_to_columnar, is_arrow

    if is_arrow(data):
        return arrow_to_columnar(data, missing, _normalize_dense)
    # polars (columnar adapter; reference: ColumnarAdapter src/data/adapter.h
    # + python-package data.py _from_polars)
    if type(data).__module__.split(".")[0] == "polars":
        import polars as pl

        feature_names = list(data.columns)
        feature_types = []
        cols = []
        cat_categories = {}
        for fi, c in enumerate(data.columns):
            s = data[c]
            if s.dtype in (pl.Categorical, pl.Enum):
                cat_categories[fi] = [str(v) for v in
                                      s.cat.get_categories().to_list()]
                codes = s.to_physical().cast(pl.Float32).to_numpy().copy()
                cols.append(codes)
                feature_types.append("c")
            else:
                cols.append(s.cast(pl.Float32).to_numpy().copy())
                feature_types.append("q")
        arr = (np.stack(cols, axis=1) if cols
               else np.zeros((len(data), 0), np.float32))
        return (("dense",
                 _normalize_dense(arr, missing, np, feature_types),
                 cat_categories),
                feature_names, feature_types)
    # pandas
    if hasattr(data, "iloc") and hasattr(data, "columns"):
        feature_names = [str(c) for c in data.columns]
        feature_types = []
        cols = []
        cat_categories = {}
        for fi, c in enumerate(data.columns):
            col = data[c]
            if str(col.dtype) == "category":
                codes = col.cat.codes.to_numpy().astype(np.float32)
                codes[codes < 0] = np.nan  # pandas encodes NaN as -1
                cols.append(codes)
                feature_types.append("c")
                # category VALUES, for train->inference recode
                # (reference: src/encoder/ordinal.h Recode)
                cat_categories[fi] = [
                    v.item() if hasattr(v, "item") else v
                    for v in col.cat.categories.tolist()]
            else:
                cols.append(col.to_numpy().astype(np.float32))
                feature_types.append("q" if col.dtype.kind == "f" else "int")
        arr = np.stack(cols, axis=1) if cols else np.zeros((len(data), 0), np.float32)
        return (("dense",
                 _normalize_dense(arr, missing, np, feature_types),
                 cat_categories),
                feature_names, feature_types)
    # scipy sparse
    if hasattr(data, "tocsr"):
        csr = data.tocsr()
        return ("csr", (np.asarray(csr.indptr), np.asarray(csr.indices),
                        np.asarray(csr.data, dtype=np.float32), csr.shape)), None, None
    arr = _normalize_dense(np.asarray(data, dtype=np.float32), missing, np)
    return ("dense", arr), feature_names, feature_types


class DMatrix:
    """In-memory data matrix (reference: core.py:666 DMatrix, data.h:549).

    Holds raw host data + MetaInfo; binning to an EllpackPage happens lazily at
    training time (``ensure_ellpack``) or eagerly for QuantileDMatrix.
    """

    def __init__(
        self,
        data: Any,
        label: Any = None,
        *,
        weight: Any = None,
        base_margin: Any = None,
        missing: float = np.nan,
        feature_names: Optional[Sequence[str]] = None,
        feature_types: Optional[Sequence[str]] = None,
        group: Any = None,
        qid: Any = None,
        label_lower_bound: Any = None,
        label_upper_bound: Any = None,
        feature_weights: Any = None,
        nthread: Optional[int] = None,
        enable_categorical: bool = False,
        silent: bool = False,
    ) -> None:
        _prev_nthread = None
        if nthread is not None:
            # pool width scoped to this construction (the reference's
            # DMatrix nthread semantics); restored in the finally below —
            # results are bitwise-neutral either way
            from ..utils import native

            _prev_nthread = native.get_nthread()
            native.set_nthread(int(nthread))
        try:
            self._init_ingest(data, label, weight, base_margin, missing,
                              feature_names, feature_types, group, qid,
                              label_lower_bound, label_upper_bound,
                              feature_weights, enable_categorical)
        finally:
            if _prev_nthread is not None:
                from ..utils import native

                native.set_nthread(_prev_nthread)

    def _init_ingest(self, data, label, weight, base_margin, missing,
                     feature_names, feature_types, group, qid,
                     label_lower_bound, label_upper_bound, feature_weights,
                     enable_categorical) -> None:
        auto_label = auto_qid = None
        self.cat_categories = None  # {feature idx -> category values} (pandas)
        self._jax_X = None  # device-resident input (zero-copy jax.Array ingest)
        if isinstance(data, (str, os.PathLike)):
            (kind, payload), auto_names, auto_types, auto_label, auto_qid = _load_uri(
                os.fspath(data))
        elif _is_jax_array(data):
            # zero-copy device ingest: keep the array on device; host numpy is
            # materialized lazily only if a host path (raw predict, slice)
            # needs it (reference: device adapters skip the host round-trip,
            # src/data/device_adapter.cuh:67)
            import jax.numpy as jnp

            self._jax_X = _normalize_dense(
                jnp.asarray(data, dtype=jnp.float32), missing, jnp)
            kind, payload, auto_names, auto_types = "dense", None, None, None
        else:
            (kind, *rest), auto_names, auto_types = _to_numpy_2d(data, missing)
            payload = rest[0]
            if len(rest) > 1 and rest[1]:
                self.cat_categories = rest[1]
        self._kind = kind
        if kind == "dense":
            self._dense: Optional[np.ndarray] = payload
            self._csr = None
            num_row, num_col = (payload.shape if payload is not None
                                else self._jax_X.shape)
        else:
            self._dense = None
            self._csr = payload
            num_row, num_col = payload[3]
        self.info = MetaInfo(num_row=num_row, num_col=num_col)
        if label is None and auto_label is not None:
            self.set_label(auto_label)  # labels embedded in the data file
        if qid is None and auto_qid is not None:
            self.set_qid(auto_qid)
        if label is not None:
            self.set_label(label)
        if weight is not None:
            self.set_weight(weight)
        if base_margin is not None:
            self.set_base_margin(base_margin)
        if group is not None:
            self.set_group(group)
        if qid is not None:
            self.set_qid(qid)
        if label_lower_bound is not None:
            self.info.label_lower_bound = np.asarray(label_lower_bound, np.float32)
        if label_upper_bound is not None:
            self.info.label_upper_bound = np.asarray(label_upper_bound, np.float32)
        if feature_weights is not None:
            self.info.feature_weights = np.asarray(feature_weights, np.float32)
        self.info.feature_names = list(feature_names) if feature_names else auto_names
        self.info.feature_types = list(feature_types) if feature_types else auto_types
        self.info.validate()
        self._ellpack: Optional[EllpackPage] = None
        self._max_bin_built: Optional[int] = None

    # ---- setters (reference: core.py set_info family) ----
    def set_label(self, label: Any) -> None:
        arr = np.asarray(label, dtype=np.float32)
        if arr.shape[0] != self.num_row():
            raise ValueError(
                f"label has {arr.shape[0]} entries but data has {self.num_row()} rows"
            )
        self.info.label = arr.reshape(self.num_row(), -1)
        if self.info.label.shape[1] == 1:
            self.info.label = self.info.label[:, 0]

    def set_weight(self, weight: Any) -> None:
        self.info.weight = np.asarray(weight, dtype=np.float32).reshape(-1)

    def set_base_margin(self, margin: Any) -> None:
        self.info.base_margin = np.asarray(margin, dtype=np.float32)

    def set_group(self, group: Any) -> None:
        g = np.asarray(group, dtype=np.int64)
        self.info.group_ptr = np.concatenate([[0], np.cumsum(g)]).astype(np.int64)
        self._bump_group_version()

    def set_qid(self, qid: Any) -> None:
        q = np.asarray(qid)
        if len(q) == 0:
            return
        change = np.nonzero(np.diff(q) != 0)[0] + 1
        self.info.group_ptr = np.concatenate([[0], change, [len(q)]]).astype(np.int64)
        self._bump_group_version()

    def _bump_group_version(self) -> None:
        """Monotone counter so Booster caches keyed on the group layout
        cannot alias after allocator address reuse."""
        self.group_version = getattr(self, "group_version", 0) + 1

    # ---- shape ----
    def num_row(self) -> int:
        return self.info.num_row

    def num_col(self) -> int:
        return self.info.num_col

    def get_label(self) -> np.ndarray:
        return self.info.label if self.info.label is not None else np.zeros(self.num_row(), np.float32)

    def get_weight(self) -> Optional[np.ndarray]:
        return self.info.weight

    @property
    def feature_names(self):
        return self.info.feature_names

    @property
    def feature_types(self):
        return self.info.feature_types

    # ---- raw views for prediction ----
    def get_categories(self) -> Optional[dict]:
        """Category values per categorical feature, keyed by feature name (or
        index when unnamed), as captured from the input frame (pandas/polars/
        arrow dictionary columns).  None for purely numeric inputs
        (reference: ``XGDMatrixGetCategories``, src/data/cat_container.h)."""
        return categories_by_name(self.cat_categories,
                                  self.info.feature_names)

    def host_dense(self) -> np.ndarray:
        """Dense f32 view with NaN missing (prediction walks raw values)."""
        if self._dense is not None:
            return self._dense
        if self._jax_X is not None:  # lazy device -> host materialization
            self._dense = np.asarray(self._jax_X)
            return self._dense
        return self.host_dense_rows(0, self.num_row())

    def host_dense_rows(self, lo: int, hi: int) -> np.ndarray:
        """Densify only rows [lo, hi) — the bounded-memory window used by the
        streamed predictor (reference: gpu_predictor.cu:43-90 splits a
        SparsePage loader from the dense loader for the same reason)."""
        if self._dense is not None or self._jax_X is not None:
            return self.host_dense()[lo:hi]
        indptr, indices, values, (R, F) = self._csr
        hi = min(hi, R)
        out = np.full((hi - lo, F), np.nan, dtype=np.float32)
        a, b = indptr[lo], indptr[hi]
        row_of = np.repeat(np.arange(lo, hi), np.diff(indptr[lo : hi + 1])) - lo
        out[row_of, indices[a:b]] = values[a:b]
        return out

    def _device_dense(self):
        """Device f32 view of dense data, uploaded at most once — the sketch
        and the Ellpack build share it instead of each shipping X over the
        host->device link (at tunnel bandwidths that transfer dominates
        QuantileDMatrix construction)."""
        if self._jax_X is None:
            import jax.numpy as jnp

            self._jax_X = jnp.asarray(self._dense, dtype=jnp.float32)
        return self._jax_X

    def cat_mask(self) -> Optional[np.ndarray]:
        """(F,) bool — which features are categorical ('c' feature type)."""
        ft = self.info.feature_types
        if not ft or "c" not in ft:
            return None
        return np.asarray([t == "c" for t in ft], dtype=bool)

    # ---- binning ----
    def ensure_ellpack(self, max_bin: int = 256, sketch_weights: Optional[np.ndarray] = None,
                       ref: Optional["DMatrix"] = None,
                       distributed: bool = False,
                       row_align: int = 1024) -> EllpackPage:
        if (self._ellpack is not None and self._max_bin_built == max_bin
                and self._ellpack.n_padded % row_align == 0):
            return self._ellpack
        if self._ellpack is not None and self._max_bin_built == max_bin:
            # alignment-only rebuild (n_devices changed): reuse the built
            # cuts — re-sketching would waste the work and, distributed, a
            # rank whose padding already divides row_align would take the
            # cache hit above while its peers re-enter the sketch
            # collectives alone (desync)
            cuts = self._ellpack.cuts
        elif ref is not None and ref._ellpack is not None:
            cuts = ref._ellpack.cuts  # GetCutsFromRef (quantile_dmatrix.cc:19)
        elif distributed and self._kind == "dense":
            # every process holds a row shard: merge the per-shard quantile
            # summaries into shared cuts (quantile.cc:397 AllreduceV analogue)
            from .quantile import sketch_distributed

            cuts = sketch_distributed(self.host_dense(), max_bin,
                                      weights=sketch_weights,
                                      cat_mask=self.cat_mask())
        elif self._kind == "dense":
            # weighted / categorical sketches run on host — feed them the
            # host array when we already have one rather than bouncing the
            # device upload back down
            cm = self.cat_mask()
            host_sketch = sketch_weights is not None or (
                cm is not None and cm.any())
            # host sketches get the cached host copy (one D2H transfer, reused
            # by later host paths) instead of bouncing the device array down
            sk_X = self.host_dense() if host_sketch else self._device_dense()
            cuts = sketch_dense(sk_X, max_bin, weights=sketch_weights,
                                cat_mask=cm)
        else:
            indptr, indices, values, (R, F) = self._csr
            cuts = sketch_csr(indptr, indices, values, F, max_bin,
                              weights=sketch_weights, cat_mask=self.cat_mask(),
                              distributed=distributed)
        if self._kind == "dense":
            self._ellpack = build_ellpack(self._device_dense(), cuts,
                                          row_align=row_align)
            if self._dense is not None:
                self._jax_X = None  # binned; drop the duplicate device copy
        else:
            indptr, indices, values, (R, F) = self._csr
            self._ellpack = build_ellpack_csr(indptr, indices, values, F, cuts,
                                              row_align=row_align)
        self._max_bin_built = max_bin
        return self._ellpack

    def slice(self, rindex: Sequence[int]) -> "DMatrix":
        """Row slice (reference: XGDMatrixSliceDMatrix) — used by cv()."""
        idx = np.asarray(rindex, dtype=np.int64)
        if self._kind == "dense":
            out = DMatrix(self.host_dense()[idx])
        else:
            import scipy.sparse as sp

            indptr, indices, values, shape = self._csr
            csr = sp.csr_matrix((values, indices, indptr), shape=shape)[idx]
            out = DMatrix(csr)
        info = self.info
        if info.label is not None:
            out.info.label = info.label[idx]
        if info.weight is not None:
            out.info.weight = info.weight[idx]
        if info.base_margin is not None:
            out.info.base_margin = info.base_margin[idx]
        if info.label_lower_bound is not None:
            out.info.label_lower_bound = info.label_lower_bound[idx]
        if info.label_upper_bound is not None:
            out.info.label_upper_bound = info.label_upper_bound[idx]
        if info.group_ptr is not None:
            # re-derive query groups for the selected rows (qid per row -> regroup)
            qid = np.repeat(np.arange(len(info.group_ptr) - 1), np.diff(info.group_ptr))
            out.set_qid(qid[idx])
        out.info.feature_weights = info.feature_weights
        out.info.feature_names = info.feature_names
        out.info.feature_types = info.feature_types
        return out


class QuantileDMatrix(DMatrix):
    """Eagerly-binned DMatrix (reference: core.py:1434, iterative_dmatrix.h:34).

    Sketches and bins at construction; ``ref=`` reuses the training cuts so
    validation data lands in identical bins.
    """

    def __init__(self, data: Any, label: Any = None, *, max_bin: int = 256,
                 ref: Optional[DMatrix] = None, **kwargs: Any) -> None:
        super().__init__(data, label, **kwargs)
        self.max_bin = max_bin
        self.ensure_ellpack(max_bin=max_bin, ref=ref)
