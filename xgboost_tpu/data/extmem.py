"""External-memory data: DataIter + ExtMemQuantileDMatrix.

Reference: python-package/xgboost/core.py:265 (DataIter callback protocol),
src/data/extmem_quantile_dmatrix.{h,cc} (the modern external-memory path:
binned Ellpack pages in a host cache, re-streamed to device every histogram
pass) and src/data/ellpack_page_source.h:37-70 (EllpackCacheInfo/MemCache).

TPU design: pass 1 streams user batches through the device sketcher and merges
per-batch quantile grids (the fixed-size analogue of the reference's
AllreduceV summary merge); pass 2 bins each batch on device and parks the
compressed page in HOST RAM (optionally a disk-backed memmap — the
``on_host=False`` spill path).  Training streams pages host->HBM with
one-page-ahead prefetch (reference: n_prefetch_batches,
sparse_page_source.h:293).
"""
from __future__ import annotations

import tempfile
from typing import Any, Callable, List, Optional

import numpy as np

from .dmatrix import DMatrix, MetaInfo
from .ellpack import build_ellpack
from .quantile import HistogramCuts, StreamingSketch

PAGE_ALIGN = 1024  # rows; keeps every page a whole number of hist row tiles


class PageCorruptError(RuntimeError):
    """An external-memory page failed its integrity check at decode and a
    one-shot re-read from the backing store failed too.  Raised instead of
    ever handing corrupted bins to the histogram kernels; in a
    multi-process run the worker dies loudly on it and the tracker abort
    fan-out stops the peers (docs/reliability.md "Integrity & chaos")."""


def _page_crc(arr: np.ndarray) -> int:
    import zlib

    return zlib.crc32(np.ascontiguousarray(arr))


def _retry_pause() -> None:
    """Deterministic pause before a page's one-shot re-read: its own
    (op, seed) RNG stream, so interleaving with any other backoff user
    cannot perturb either schedule (pinned by tests/test_integrity.py)."""
    from ..reliability import integrity as _integrity
    from ..reliability.retry import backoff_delays

    _integrity.retried("page")
    time.sleep(next(backoff_delays(1, base=0.005, max_delay=0.05,
                                   op="integrity.page", seed=0)))


def _verify_decoded(out, crc: int, *, what: str, attempt: int):
    """One decode attempt's integrity gate, shared by the compressed and
    disk page paths.  ``out`` is the decoded payload — bytes, or a
    C-contiguous ndarray verified in place (no copy on the no-fault
    path).  Applies the ``extmem.page_decode`` fault seam (``corrupt``
    flips a byte in a copy — the deterministic stand-in for a bit flip
    during decompress/read; ``exception`` raises), then verifies the page
    CRC recorded at construction.  Returns the verified payload, or None
    when this attempt failed verification (the caller retries once from
    the backing store, then fails loud)."""
    import zlib

    from ..reliability import integrity as _integrity
    from ..reliability.faults import corrupt_bytes, maybe_inject

    spec = maybe_inject("extmem.page_decode", round=attempt)
    if spec is not None and spec.kind == "corrupt":
        if isinstance(out, np.ndarray):
            out = memoryview(np.ascontiguousarray(out)).cast("B")
        out = corrupt_bytes(out, spec)
    buf = out if isinstance(out, (bytes, bytearray)) \
        else np.ascontiguousarray(out)
    if zlib.crc32(buf) == crc:
        return out
    _integrity.corrupt_detected("page")
    if attempt == 0:
        _retry_pause()
        return None
    raise PageCorruptError(
        f"{what}: page CRC mismatch after decode AND after one re-read "
        "from the backing store — refusing to train on corrupted bins")


# ---------------------------------------------------------------------------
# Telemetry: the xtb_extmem_* family (docs/observability.md catalog).
# Decode/wait/overlap make the prefetch pipeline's behaviour observable —
# decode seconds spent off the critical path (overlap) vs blocking the
# consumer (wait) — and the cache counters say how often a page touch was
# served without paying the decode again.
# ---------------------------------------------------------------------------
_instruments = None


def instruments():
    """(decode_s, wait_s, overlap_s, pages, bytes, hits, misses) counters."""
    global _instruments
    if _instruments is None:
        from ..telemetry.registry import get_registry

        reg = get_registry()
        _instruments = (
            reg.counter("xtb_extmem_decode_seconds_total",
                        "seconds decoding/staging external-memory pages "
                        "(zstd decompress + host->device put), wherever "
                        "they ran"),
            reg.counter("xtb_extmem_wait_seconds_total",
                        "seconds the page consumer blocked waiting for a "
                        "page to be ready (decode not hidden under "
                        "compute)"),
            reg.counter("xtb_extmem_overlap_seconds_total",
                        "decode seconds hidden under compute: per page, "
                        "max(0, decode - consumer wait)"),
            reg.counter("xtb_extmem_pages_loaded_total",
                        "external-memory pages staged for compute"),
            reg.counter("xtb_extmem_page_bytes_total",
                        "bytes of staged (decoded) page data"),
            reg.counter("xtb_extmem_cache_hits_total",
                        "page touches served from the host/device page "
                        "cache"),
            reg.counter("xtb_extmem_cache_misses_total",
                        "page touches that paid a decode (or device "
                        "re-stage)"),
        )
    return _instruments


class CompressedPage:
    """Zstd-compressed binned page, in host RAM or spilled to disk.

    The role of the reference's page compression (compressed_iterator.h
    bit-packing + device_compression.cu nvCOMP): binned codes are tiny-
    alphabet integers, so entropy coding crushes them (subsuming manual
    bit-packing) and every histogram pass pays one decompress on the host
    side of the H2D copy.  Transparent to consumers: ``shape``/``dtype``
    attributes plus ``__array__`` (``np.ascontiguousarray``/``jnp.asarray``
    decompress on touch).
    """

    # __weakref__ so the page cache can hang its eviction finalizer here
    __slots__ = ("shape", "dtype", "_blob", "_path", "nbytes_compressed",
                 "crc", "__weakref__")

    def __init__(self, arr: np.ndarray, path: Optional[str] = None):
        import zstandard as zstd

        raw = np.ascontiguousarray(arr)
        blob = zstd.ZstdCompressor(level=3).compress(raw.tobytes())
        self.shape = raw.shape
        self.dtype = raw.dtype
        self.nbytes_compressed = len(blob)
        # CRC over the UNCOMPRESSED bytes, verified after every decompress:
        # catches blob damage zstd happens to decode anyway AND decode-side
        # corruption, one check for both (docs/reliability.md)
        self.crc = _page_crc(raw)
        if path is not None:
            with open(path, "wb") as fh:
                fh.write(blob)
            self._blob, self._path = None, path
        else:
            self._blob, self._path = blob, None

    def _decompress(self) -> bytes:
        """One decode attempt: (re-)read the blob, decompress.  A blob zstd
        itself rejects (truncated / framing damage) is a detected
        corruption, reported like a CRC miss."""
        import zstandard as zstd

        from ..reliability import integrity as _integrity

        blob = self._blob
        if blob is None:
            with open(self._path, "rb") as fh:
                blob = fh.read()
        try:
            return zstd.ZstdDecompressor().decompress(blob)
        except zstd.ZstdError as e:
            _integrity.corrupt_detected("page")
            raise PageCorruptError(
                f"page blob undecodable ({e}); truncated or bit-flipped "
                "compressed stream") from e

    def __array__(self, dtype=None, copy=None):
        hits, misses = instruments()[5:7]
        cached = _host_page_cache_get(self)
        if cached is not None:
            hits.inc()
            return cached if dtype is None else cached.astype(dtype)
        misses.inc()
        raw = None
        for attempt in (0, 1):
            try:
                decoded = self._decompress()
            except PageCorruptError:
                # a zstd-rejected blob gets the same retry-once-from-the-
                # backing-store contract a CRC miss gets (a transient
                # in-memory flip in the framing heals on re-read)
                if attempt == 0:
                    _retry_pause()
                    continue
                raise
            raw = _verify_decoded(decoded, self.crc,
                                  what=f"compressed page {self._path or ''}",
                                  attempt=attempt)
            if raw is not None:
                break
        out = np.frombuffer(raw, dtype=self.dtype).reshape(self.shape)
        _host_page_cache_put(self, out)
        return out if dtype is None else out.astype(dtype)


class DiskPage:
    """Uncompressed page spilled to a ``.npy`` file (the no-zstandard
    ``on_host=False`` fallback), wrapped so every disk read passes the
    same CRC-verify / retry-once / fail-loud gate the compressed decode
    does — disk is a failure surface whether or not the bytes were
    entropy-coded.  Same consumer contract as :class:`CompressedPage`:
    ``shape`` / ``dtype`` / ``__array__`` only."""

    __slots__ = ("shape", "dtype", "_path", "crc", "__weakref__")

    def __init__(self, arr: np.ndarray, path: str):
        raw = np.ascontiguousarray(arr)
        self.shape = raw.shape
        self.dtype = raw.dtype
        self.crc = _page_crc(raw)
        mm = np.lib.format.open_memmap(path, mode="w+", dtype=raw.dtype,
                                       shape=raw.shape)
        mm[:] = raw
        mm.flush()
        del mm
        self._path = path

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) * self.dtype.itemsize

    def __array__(self, dtype=None, copy=None):
        hits, misses = instruments()[5:7]
        cached = _host_page_cache_get(self)
        if cached is not None:
            hits.inc()
            return cached if dtype is None else cached.astype(dtype)
        misses.inc()
        raw = None
        for attempt in (0, 1):
            try:
                arr = np.load(self._path)
            except (ValueError, OSError) as e:
                from ..reliability import integrity as _integrity

                _integrity.corrupt_detected("page")
                if attempt == 0:  # same retry-once contract as a CRC miss
                    _retry_pause()
                    continue
                raise PageCorruptError(
                    f"disk page {self._path} unreadable ({e}); damaged "
                    "npy header or truncated file") from e
            # verified IN PLACE over the loaded array's buffer — no
            # per-decode tobytes copy on the no-fault hot path
            raw = _verify_decoded(arr, self.crc,
                                  what=f"disk page {self._path}",
                                  attempt=attempt)
            if raw is not None:
                break
        out = (raw if isinstance(raw, np.ndarray)
               else np.frombuffer(raw, dtype=self.dtype).reshape(self.shape))
        _host_page_cache_put(self, out)
        return out if dtype is None else out.astype(dtype)


# ---------------------------------------------------------------------------
# Page cache (LRU, one shared byte budget, weakref-evicted).
#
# The reference keeps recently-used uncompressed pages host-resident too
# (sparse_page_source.h cache + the cache_host_ratio knob): streaming
# training touches every page once per LEVEL, so without this each of the
# depth x rounds passes pays the full zstd decode again.  Two entry kinds
# share ONE budget (XTB_EXTMEM_HOST_CACHE_MB, default 1024; 0 disables):
#  - "host": the decompressed numpy bins (populated by __array__);
#  - "dev":  the committed jax.Array on the CPU backend, where device
#    memory IS host memory (tree/stream.py skips the per-level memcpy).
# TPU never uses the "dev" kind — streaming exists because HBM cannot hold
# the pages.  Entries hold no strong reference to the owning page; a
# weakref finalizer evicts them when the page (and so its DMatrix) dies.
# ---------------------------------------------------------------------------
import threading
import weakref
from collections import OrderedDict

_PAGE_CACHE: "OrderedDict" = OrderedDict()  # (id(page), kind) -> array
_PAGE_CACHE_BYTES = 0
# prefetch worker threads and the consumer touch the cache concurrently;
# every read/write of the two globals above goes through this lock
_CACHE_LOCK = threading.Lock()


# one-shot announcements of governed extmem ladder steps (benign racy:
# a duplicate flight event at worst); keyed so a restore re-arms them
_GOV_ANNOUNCED = {"prefetch": False, "cache_level": 0}


def _host_cache_budget() -> int:
    """Page-cache byte budget: the XTB_EXTMEM_HOST_CACHE_MB env knob
    scaled by the resource governor's memory ladder — level 1 cuts it to
    a quarter, level 2+ disables caching entirely (every page touch
    recomputes from its backing store; bitwise-identical, just slower —
    docs/reliability.md "Resource pressure & graceful degradation")."""
    import os

    from ..reliability import resources as _resources

    try:
        mb = float(os.environ.get("XTB_EXTMEM_HOST_CACHE_MB", "1024"))
    except ValueError:
        mb = 1024.0
    gov = _resources.get_governor()
    scale = gov.memory_scale()
    level = gov.level("memory")
    if level != _GOV_ANNOUNCED["cache_level"]:
        _GOV_ANNOUNCED["cache_level"] = level
        if level > 0:
            _resources.degraded_event(
                "extmem", "cache_budget_scaled", memory_level=level,
                scale=scale)
    return int(mb * 2**20 * scale)


def _page_cache_evict_page(pid: int) -> None:
    global _PAGE_CACHE_BYTES
    with _CACHE_LOCK:
        for kind in ("host", "dev"):
            arr = _PAGE_CACHE.pop((pid, kind), None)
            if arr is not None:
                _PAGE_CACHE_BYTES -= arr.nbytes


def _page_cache_get(page, kind: str):
    with _CACHE_LOCK:
        hit = _PAGE_CACHE.get((id(page), kind))
        if hit is not None:
            _PAGE_CACHE.move_to_end((id(page), kind))
        return hit


def _page_cache_put(page, kind: str, arr) -> None:
    global _PAGE_CACHE_BYTES
    budget = _host_cache_budget()
    try:
        finalizer = weakref.finalize(page, _page_cache_evict_page, id(page))
    except TypeError:
        return  # not weakref-able: never cache (no safe eviction)
    with _CACHE_LOCK:
        if arr.nbytes > budget or (id(page), kind) in _PAGE_CACHE:
            finalizer.detach()
            return
        _PAGE_CACHE[(id(page), kind)] = arr
        _PAGE_CACHE_BYTES += arr.nbytes
        while _PAGE_CACHE_BYTES > budget and _PAGE_CACHE:
            _, old = _PAGE_CACHE.popitem(last=False)
            _PAGE_CACHE_BYTES -= old.nbytes


def _host_page_cache_get(page):
    return _page_cache_get(page, "host")


def _host_page_cache_put(page, arr: np.ndarray) -> None:
    _page_cache_put(page, "host", arr)


def device_page_cache_get_or_put(page, make):
    """CPU-backend committed-page cache (tree/stream.py _put_page): holds
    the jax.Array so the per-level device_put memcpy disappears, under the
    same shared budget as the decompress cache.  Never used on TPU."""
    hits, misses = instruments()[5:7]
    hit = _page_cache_get(page, "dev")
    if hit is not None:
        hits.inc()
        return hit
    # one count per page touch: a compressed/disk page's make() re-enters
    # __array__, which scores the decode itself (host-cache hit = decode
    # avoided — the ratio that matters); only in-RAM uncompressed pages,
    # which never pass __array__, are scored here
    if not isinstance(page, (CompressedPage, DiskPage)):
        misses.inc()
    arr = make()  # expensive: decode + device commit, outside the lock
    global _PAGE_CACHE_BYTES
    with _CACHE_LOCK:
        # the committed array supersedes the decompressed numpy copy — same
        # bytes on the CPU backend, no reason to hold both
        host = _PAGE_CACHE.pop((id(page), "host"), None)
        if host is not None:
            _PAGE_CACHE_BYTES -= host.nbytes
    _page_cache_put(page, "dev", arr)
    return arr


def _zstd_available() -> bool:
    try:
        import zstandard  # noqa: F401

        return True
    except ImportError:
        return False


# ---------------------------------------------------------------------------
# Overlapped page scheduler.
#
# The reference streams compressed pages under compute with an N-ahead
# prefetch window (sparse_page_source.h:293 n_prefetch_batches; the
# out-of-core GPU paper's overlap pipeline, arXiv:2005.09148 §4).  Here a
# small persistent thread pool decodes (zstd -> numpy) and stages
# (device_put) pages while the consumer's histogram kernels run, so the
# decode hides entirely under compute; the consumer blocks only when it
# outruns the window.  One pool is shared by every scheduler instance —
# page streaming is level-sequential, so two concurrent windows never
# compete for more than the window width.
# ---------------------------------------------------------------------------
import time

_POOL = None
_POOL_LOCK = threading.Lock()


def _prefetch_pool():
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            import concurrent.futures

            _POOL = concurrent.futures.ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="xtb-extmem-prefetch")
        return _POOL


def prefetch_lookahead(default: int = 2) -> int:
    """Prefetch window width (pages in flight beyond the one being
    consumed).  XTB_EXTMEM_PREFETCH_PAGES overrides; 0 disables the pool
    (synchronous staging).  Under memory or fd pressure the resource
    governor forces 0 — no decoded pages in flight beyond the consumer,
    no extra spill files open — the first step of the extmem degradation
    ladder (bitwise-identical output, pinned by tests)."""
    import os

    from ..reliability import resources as _resources

    try:
        n = int(os.environ.get("XTB_EXTMEM_PREFETCH_PAGES", str(default)))
    except ValueError:
        n = default
    n = max(n, 0)
    gov = _resources.get_governor()
    if n > 0 and not gov.prefetch_allowed():
        if not _GOV_ANNOUNCED["prefetch"]:
            _GOV_ANNOUNCED["prefetch"] = True
            _resources.degraded_event(
                "extmem", "prefetch_disabled",
                memory_level=gov.level("memory"),
                fd_level=gov.level("fd"))
        return 0
    if gov.prefetch_allowed():
        _GOV_ANNOUNCED["prefetch"] = False  # re-arm after a restore
    return n


# Deterministic pipeline-shape probe for tests (XTB_EXTMEM_EVENT_LOG=1):
# consumers append ("submit"/"wait"/"ready"/"load_sync", page_idx) and
# ("level", depth) markers in MAIN-THREAD program order, so assertions on
# it are scheduling-independent (tests/test_extmem.py).
PAGE_EVENT_LOG: List[tuple] = []


def event_log_enabled() -> bool:
    import os

    return bool(os.environ.get("XTB_EXTMEM_EVENT_LOG"))


class PageScheduler:
    """Stream a page list through the prefetch pool, N ahead.

    ``stage(page) -> staged`` runs on a pool worker (decode + device put);
    ``get(j)`` (called with strictly increasing ``j``) first submits
    through ``j + lookahead``, then blocks only until page ``j``'s decode
    lands.  ``lookahead=0`` stages synchronously in ``get`` — the
    measurement baseline where decode serializes against compute.

    Telemetry (docs/observability.md): per page, decode seconds are
    attributed as consumer ``wait`` (not hidden) vs ``overlap`` (hidden
    under compute); plus pages/bytes staged.  The ``extmem.page_load``
    fault seam fires before every stage — ``round`` matches the position
    in the streamed page list — so a mid-stream decode failure surfaces
    on the consumer as a clean exception (docs/reliability.md).
    """

    def __init__(self, pages: List[Any], stage: Callable[[Any], Any], *,
                 lookahead: Optional[int] = None,
                 events: Optional[List[tuple]] = None) -> None:
        from .. import collective

        self._pages = pages
        self._stage = stage
        self._lookahead = (prefetch_lookahead() if lookahead is None
                           else max(int(lookahead), 0))
        self._futures: dict = {}
        self._events = events
        self._ins = instruments()
        self._next = 0
        # resolve the rank HERE, on the consumer thread: thread-local
        # collective backends (the in-memory thread harness) are invisible
        # from the prefetch pool workers, so a lazy get_rank inside _load
        # would mis-attribute rank-constrained fault plans under prefetch
        try:
            self._rank = collective.get_rank()
        except Exception:  # pragma: no cover - backend mid-teardown
            self._rank = None

    @property
    def lookahead(self) -> int:
        return self._lookahead

    def _record(self, name: str, j: int) -> None:
        if self._events is not None:
            self._events.append((name, j))

    def _load(self, j: int):
        from ..reliability.faults import maybe_inject

        maybe_inject("extmem.page_load", rank=self._rank, round=j)
        t0 = time.perf_counter()
        arr = self._stage(self._pages[j])
        dt = time.perf_counter() - t0
        self._ins[0].inc(dt)
        self._ins[3].inc()
        self._ins[4].inc(float(getattr(arr, "nbytes", 0)))
        return arr, dt

    def _submit_through(self, j: int) -> None:
        stop = min(j, len(self._pages) - 1)
        while self._next <= stop:
            k = self._next
            self._record("submit", k)
            self._futures[k] = _prefetch_pool().submit(self._load, k)
            self._next += 1

    def get(self, j: int):
        from ..reliability import watchdog as _watchdog

        if self._lookahead <= 0:
            self._record("load_sync", j)
            arr, dt = self._load(j)
            self._ins[1].inc(dt)  # synchronous: the consumer waited it all
            _watchdog.progress("extmem.page", page=j)
            return arr
        self._submit_through(j + self._lookahead)
        self._record("wait", j)
        t0 = time.perf_counter()
        fut = self._futures.pop(j)
        # bounded wait under the extmem watchdog budget (XTB702): each
        # PAGE gets its own guard, so a slow-but-progressing stream never
        # escalates — only one decode wedged past the budget does (warn
        # -> all-thread stack dump -> typed failure; multi-process, the
        # loud death runs the tracker abort/regroup path)
        with _watchdog.guard("extmem.decode", page=j) as g:
            from concurrent.futures import TimeoutError as _FutTimeout

            while True:
                try:
                    arr, decode_s = fut.result(timeout=0.5)
                    break
                except _FutTimeout:
                    if g.stalled:
                        fut.cancel()
                        raise PageCorruptError(
                            f"external-memory page {j} decode stalled past "
                            f"the watchdog budget (stack dump: "
                            f"{g.stack_path}); failing loud instead of "
                            "wedging the stream")
        wait_s = time.perf_counter() - t0
        self._record("ready", j)
        self._ins[1].inc(wait_s)
        self._ins[2].inc(max(0.0, decode_s - wait_s))
        _watchdog.progress("extmem.page", page=j)
        return arr

    def close(self) -> None:
        for fut in self._futures.values():
            fut.cancel()
        self._futures.clear()


class DataIter:
    """User-defined batch iterator (reference: core.py:265).

    Subclasses implement ``next(input_data)`` — call ``input_data(data=...,
    label=..., weight=..., ...)`` and return 1, or return 0 at the end — and
    ``reset()``.
    """

    def __init__(self, cache_prefix: Optional[str] = None,
                 release_data: bool = True) -> None:
        self.cache_prefix = cache_prefix
        self.release_data = release_data

    def next(self, input_data: Callable) -> int:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


def _iterate(it: DataIter):
    """Drive a DataIter; yields dicts of the input_data kwargs per batch."""
    it.reset()
    while True:
        got: List[dict] = []

        def input_data(**kwargs):
            got.append(kwargs)
            return 1

        status = it.next(input_data)
        if not status:
            break
        if not got:
            raise RuntimeError("DataIter.next returned 1 without calling input_data")
        yield got[0]


class ExtMemQuantileDMatrix(DMatrix):
    """External-memory binned DMatrix (reference: core.py:1624,
    extmem_quantile_dmatrix.h:29).

    Pages live in host RAM (or disk when ``on_host=False``); device HBM only
    ever holds one or two pages plus the histogram.
    """

    def __init__(self, data: DataIter, *, max_bin: int = 256,
                 ref: Optional[DMatrix] = None, missing: float = np.nan,
                 on_host: bool = True, enable_categorical: bool = False,
                 cache_host_ratio: Optional[float] = None,
                 compress: bool = True, **kwargs: Any) -> None:
        if not isinstance(data, DataIter):
            raise TypeError("ExtMemQuantileDMatrix requires a DataIter")
        self._it = data
        self.max_bin = max_bin
        self.on_host = on_host
        # compression defaults on, matching the reference (Ellpack pages are
        # always compressed_iterator-packed there; decompression here costs
        # one host pass per page touch, the trade the extmem path exists
        # for); degrades gracefully when zstandard is unavailable
        if compress and not _zstd_available():
            import warnings

            warnings.warn("zstandard not installed; external-memory pages "
                          "will be stored uncompressed")
            compress = False
        self.compress = compress
        # plain ndarrays (compress=False, on_host) / DiskPage (spilled) /
        # CompressedPage — consumers only use shape/dtype/__array__
        self._pages: List[Any] = []
        self._page_rows: List[int] = []  # real rows per page
        self._spill_dir = None if on_host else tempfile.mkdtemp(prefix="xtb_pages_")

        # ---- pass 1: streaming page-wise sketch (data/quantile.py
        # StreamingSketch — per-page fixed-size grids folded one page at a
        # time, one ragged summary gather when distributed, so cuts never
        # require the full matrix resident; the out-of-core role of
        # WQuantileSketch, src/common/quantile.h:565) ----
        sketch = None
        labels, weights, margins, n_col = [], [], [], None
        cat_mask = None
        num_row = 0
        for batch in _iterate(data):
            X = np.asarray(batch["data"], dtype=np.float32)
            num_row += X.shape[0]
            if n_col is None:
                n_col = X.shape[1]
                ft = batch.get("feature_types")
                if ft is not None:
                    cat_mask = np.asarray([t == "c" for t in ft], bool)
                if ref is None:
                    sketch = StreamingSketch(n_col, max_bin,
                                             cat_mask=cat_mask)
            if "label" in batch and batch["label"] is not None:
                labels.append(np.asarray(batch["label"], np.float32))
            if batch.get("weight") is not None:
                weights.append(np.asarray(batch["weight"], np.float32))
            if batch.get("base_margin") is not None:
                margins.append(np.asarray(batch["base_margin"], np.float32))
            if ref is None:
                w_b = (np.asarray(batch["weight"], np.float32)
                       if batch.get("weight") is not None else None)
                sketch.push(X, weights=w_b)

        if ref is not None:
            # GetCutsFromRef: reuse training cuts (quantile_dmatrix.cc:19);
            # works for both in-core refs (lazy ellpack) and extmem refs
            cuts = getattr(ref, "_cuts", None)
            if cuts is None:
                cuts = ref.ensure_ellpack(max_bin=max_bin).cuts
        else:
            if sketch is None:
                # every rank's DataIter must produce >= 1 batch: n_col is
                # only learned from the first one, so a zero-batch rank
                # cannot even join the sketch's summary gather (ExtMemConfig
                # guarantees this — ShardMap gives every rank a shard;
                # direct StreamingSketch users can hold zero pages)
                raise ValueError("DataIter produced no batches")
            from .. import collective

            # each process sketched only its DataIter shard: the finalize
            # merges every rank's page grids into shared cuts, exactly like
            # the in-memory distributed path (quantile.cc:397 analogue)
            cuts = sketch.finalize(distributed=collective.is_distributed())
        self._cuts = cuts

        # metadata container
        label = np.concatenate(labels) if labels else None
        self.info = MetaInfo(num_row=num_row, num_col=n_col or 0)
        if label is not None:
            self.info.label = label
        if weights:
            self.info.weight = np.concatenate(weights)
        if margins:
            self.info.base_margin = np.concatenate(margins)
        self.info.feature_types = (
            ["c" if c else "q" for c in cat_mask] if cat_mask is not None else None
        )

        # ---- pass 2: bin pages on device, park them on host/disk ----
        # governor tick at the page-build boundary: the resource.pressure
        # seam fires here (deterministic program point — chaos plans key
        # invocation numbers off it) and real headroom on the spill
        # directory is measured when one exists
        from ..reliability import resources as _resources

        _resources.get_governor().poll(self._spill_dir)
        self._kind = "extmem"
        self._dense = None
        self._csr = None
        self._ellpack = None
        self._max_bin_built = max_bin
        for bi, batch in enumerate(_iterate(data)):
            X = np.asarray(batch["data"], dtype=np.float32)
            page = build_ellpack(X, cuts, row_align=PAGE_ALIGN)
            host_page = np.asarray(page.bins)
            if compress:
                path = (f"{self._spill_dir}/page{bi}.zst"
                        if not on_host else None)
                host_page = CompressedPage(host_page, path=path)
            elif not on_host:
                # DiskPage instead of a bare read-mode memmap: every
                # re-read from the spill file passes the CRC gate
                host_page = DiskPage(host_page,
                                     f"{self._spill_dir}/page{bi}.npy")
            self._pages.append(host_page)
            self._page_rows.append(X.shape[0])
        import jax.numpy as jnp

        self.cuts_pad = jnp.asarray(cuts.padded())
        self.n_bins = jnp.asarray(cuts.n_bins_array())
        self.info.validate()

    # geometry
    @property
    def n_padded_total(self) -> int:
        return sum(p.shape[0] for p in self._pages)

    def page_offsets(self) -> List[int]:
        offs = [0]
        for p in self._pages:
            offs.append(offs[-1] + p.shape[0])
        return offs

    def num_row(self) -> int:
        return self.info.num_row

    def num_col(self) -> int:
        return self.info.num_col

    def valid_mask(self) -> np.ndarray:
        out = np.zeros(self.n_padded_total, bool)
        off = 0
        for p, r in zip(self._pages, self._page_rows):
            out[off : off + r] = True
            off += p.shape[0]
        return out

    def padded_labels(self) -> Optional[np.ndarray]:
        if self.info.label is None:
            return None
        out = np.zeros(self.n_padded_total, self.info.label.dtype)
        off = 0
        src = 0
        for p, r in zip(self._pages, self._page_rows):
            out[off : off + r] = self.info.label[src : src + r]
            off += p.shape[0]
            src += r
        return out

    def padded_weights(self) -> Optional[np.ndarray]:
        return self._pad_rows(self.info.weight)

    def padded_base_margin(self) -> Optional[np.ndarray]:
        return self._pad_rows(self.info.base_margin)

    def _pad_rows(self, arr: Optional[np.ndarray]) -> Optional[np.ndarray]:
        if arr is None:
            return None
        shape = (self.n_padded_total,) + arr.shape[1:]
        out = np.zeros(shape, np.float32)
        off = 0
        src = 0
        for p, r in zip(self._pages, self._page_rows):
            out[off : off + r] = arr[src : src + r]
            off += p.shape[0]
            src += r
        return out

    def host_dense(self) -> np.ndarray:
        raise NotImplementedError(
            "ExtMemQuantileDMatrix does not materialize raw data; "
            "prediction streams the binned pages instead"
        )

    def ensure_ellpack(self, max_bin: int = 256, **kw):
        raise NotImplementedError("external-memory pages are pre-binned")




class _RawPageReplayIter(DataIter):
    """Replays a SparsePageDMatrix's stored raw pages (densified, missing as
    NaN) into the binned-extmem two-pass ingestion."""

    def __init__(self, owner: "SparsePageDMatrix") -> None:
        super().__init__()
        self._owner = owner
        self._i = 0

    def reset(self) -> None:
        self._i = 0

    def next(self, input_data) -> int:
        if self._i >= len(self._owner._raw_pages):
            return 0
        X = self._owner._raw_page_dense(self._i)
        input_data(data=X, **self._owner._raw_meta[self._i])
        self._i += 1
        return 1


class SparsePageDMatrix(ExtMemQuantileDMatrix):
    """Raw-CSR external-memory DMatrix (reference: SparsePageDMatrix,
    src/data/sparse_page_dmatrix.h:64): the iterator's batches spill as RAW
    CSR pages (zstd, host RAM or disk), so raw-value flows work out of
    core — prediction streams page-by-page with exact float thresholds
    against ANY model, not just one trained on this matrix's cuts.
    Training reuses the binned extmem machinery by replaying the raw pages
    through the quantile/Ellpack passes (the reference's hist path over
    SparsePage batches fills the same role)."""

    def __init__(self, data: DataIter, *, missing: float = np.nan,
                 max_bin: int = 256, ref: Optional[DMatrix] = None,
                 on_host: bool = True, compress: bool = True,
                 **kwargs: Any) -> None:
        import scipy.sparse as sp

        if not isinstance(data, DataIter):
            raise TypeError("SparsePageDMatrix requires a DataIter")
        use_zstd = compress and _zstd_available()
        raw_pages: List[Any] = []
        raw_meta: List[dict] = []
        spill = None if on_host else tempfile.mkdtemp(prefix="xtb_raw_")
        n_col = None
        for batch in _iterate(data):
            X = batch["data"]
            if sp.issparse(X):
                csr = sp.csr_matrix(X).astype(np.float32)
                vals = csr.data
                keep = np.isfinite(vals)
                if missing is not None and not np.isnan(missing):
                    keep &= vals != np.float32(missing)
                if not keep.all():
                    coo = csr.tocoo()
                    csr = sp.csr_matrix(
                        (coo.data[keep], (coo.row[keep], coo.col[keep])),
                        shape=csr.shape)
            else:
                Xd = np.asarray(X, np.float32)
                mask = np.isfinite(Xd)
                if missing is not None and not np.isnan(missing):
                    mask &= Xd != np.float32(missing)
                rows, cols = np.nonzero(mask)  # keeps explicit valid zeros
                csr = sp.csr_matrix((Xd[rows, cols], (rows, cols)),
                                    shape=Xd.shape)
            if n_col is None:
                n_col = csr.shape[1]
            elif csr.shape[1] != n_col:
                raise ValueError("batches disagree on feature count")

            def _store(arr, tag, i=len(raw_pages)):
                arr = np.ascontiguousarray(arr)
                if use_zstd:
                    path = (None if spill is None else
                            f"{spill}/p{i}_{tag}.zst")
                    return CompressedPage(arr, path)
                if spill is not None:
                    # on_host=False without zstd: CRC-gated disk spill,
                    # same fallback the binned pages use
                    return DiskPage(arr, f"{spill}/p{i}_{tag}.npy")
                return arr

            raw_pages.append((_store(csr.indptr.astype(np.int64), "ip"),
                              _store(csr.indices.astype(np.int32), "ix"),
                              _store(csr.data.astype(np.float32), "va"),
                              csr.shape))
            raw_meta.append({k: np.asarray(v) for k, v in batch.items()
                             if k != "data" and v is not None})
        if not raw_pages:
            raise ValueError("iterator produced no batches")
        self._raw_pages = raw_pages
        self._raw_meta = raw_meta
        self.has_raw_pages = True
        # binned representation for training: replay the raw pages (missing
        # is already structural NaN, so the sentinel is normalized away)
        super().__init__(_RawPageReplayIter(self), max_bin=max_bin, ref=ref,
                         missing=np.nan, on_host=on_host, compress=compress,
                         **kwargs)

    def _raw_page_dense(self, i: int) -> np.ndarray:
        """Densify raw page i: absent entries are NaN (missing)."""
        import scipy.sparse as sp

        ip, ix, va, shape = self._raw_pages[i]
        csr = sp.csr_matrix((np.asarray(va), np.asarray(ix), np.asarray(ip)),
                            shape=shape)
        X = np.full(shape, np.nan, np.float32)
        coo = csr.tocoo()
        X[coo.row, coo.col] = coo.data
        return X

    def raw_dense_pages(self):
        """Yield each raw page densified (rows_i, F) — bounded memory."""
        for i in range(len(self._raw_pages)):
            yield self._raw_page_dense(i)


class ExtMemConfig:
    """Multi-process out-of-core training config for
    ``train(params, ExtMemConfig(...))`` (docs/extmem.md).

    Composes the pieces that each work alone into one full-dataset
    multi-process run: every tracker/relay rank owns a page shard
    (:class:`~xgboost_tpu.elastic.ShardMap` round-robin over
    ``num_shards``), builds its :class:`ExtMemQuantileDMatrix` from the
    :class:`DataIter` returned by ``data_fn``, the streaming page-wise
    sketch merges cuts in one ragged gather, and the per-level histogram
    allreduce rides the existing collective (tracker relay on CPU).

    ``data_fn(shard_map, rank, world)`` returns the rank's
    :class:`DataIter` — one ``input_data(...)`` batch per owned page — or
    ``(DataIter, evals)`` to supply evaluation sets too.  Launch the ranks
    with :func:`xgboost_tpu.launcher.run_distributed`; a single process
    (world 1) works unchanged.

    ``num_shards`` defaults to the world size (one page shard per rank);
    ``max_bin`` / ``on_host`` / ``compress`` forward to
    :class:`ExtMemQuantileDMatrix`.
    """

    def __init__(self, data_fn: Callable[..., Any], *,
                 num_shards: Optional[int] = None, max_bin: int = 256,
                 on_host: bool = True, compress: bool = True,
                 enable_categorical: bool = False) -> None:
        if not callable(data_fn):
            raise TypeError("ExtMemConfig.data_fn must be callable")
        self.data_fn = data_fn
        self.num_shards = int(num_shards) if num_shards is not None else None
        self.max_bin = int(max_bin)
        self.on_host = bool(on_host)
        self.compress = bool(compress)
        self.enable_categorical = bool(enable_categorical)

    def build(self):
        """(dtrain, evals) for this rank — called by ``train()``."""
        from .. import collective
        from ..elastic import ShardMap

        rank, world = collective.get_rank(), collective.get_world_size()
        smap = ShardMap.create(self.num_shards or world, world)
        built = self.data_fn(smap, rank, world)
        evals: List[Any] = []
        if isinstance(built, tuple):
            built, ev = built
            evals = list(ev) if ev else []
        if not isinstance(built, DataIter):
            raise TypeError(
                "ExtMemConfig.data_fn must return a DataIter (or a "
                f"(DataIter, evals) pair); got {type(built).__name__}")
        dtrain = ExtMemQuantileDMatrix(
            built, max_bin=self.max_bin, on_host=self.on_host,
            compress=self.compress,
            enable_categorical=self.enable_categorical)
        return dtrain, evals
