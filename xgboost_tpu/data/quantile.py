"""Quantile sketch -> histogram bin boundaries.

TPU-native equivalent of the reference's quantile sketching + ``HistogramCuts``
(src/common/quantile.h:565 WQuantileSketch, src/common/hist_util.h:39-106
HistogramCuts, GPU fused sketch src/common/quantile.cu).  The reference runs a
GK merge-prune summary per feature; on TPU the data already lives on device as a
dense array, so we compute (weighted) quantiles directly with a device sort —
O(R log R) on the sorted axis, one pass, no summary machinery — and finalize the
ragged per-feature cut arrays on host.  Distributed merging (quantile.cc:397-442
AllreduceV of summaries) becomes an all-gather of fixed-size per-shard quantile
grids (see parallel/collective.py).

Cut semantics match the reference (hist_util.cc):
 - bin b of feature f covers values v with cuts[b-1] <= v < cuts[b]
   (bin index = count of cuts <= v, i.e. searchsorted side='right');
 - the last cut is strictly greater than the feature max so every finite value
   lands in a valid bin;
 - ``min_vals`` records a value strictly below the feature min.
"""
from __future__ import annotations

import dataclasses
import secrets
from typing import List, Optional, Sequence

import numpy as np


def _fresh_cuts_token() -> int:
    """Collision-proof across processes: cuts_token survives Booster pickling,
    so a process-local counter could falsely match an unpickled model's trees
    against an unrelated DMatrix's cuts (each process's first cuts would share
    token 1) and reuse stale split_bins."""
    return secrets.randbits(63)


@dataclasses.dataclass
class HistogramCuts:
    """Bin boundaries (reference: src/common/hist_util.h:39-106).

    ``cut_ptrs``  : (F+1,) int32  — CSR offsets into ``cut_values``.
    ``cut_values``: (total_bins,) f32 — ascending per-feature upper bounds.
    ``min_vals``  : (F,) f32 — strictly below each feature's min.
    """

    cut_ptrs: np.ndarray
    cut_values: np.ndarray
    min_vals: np.ndarray
    # process-unique identity: trees grown against these cuts record the token
    # so binned predict routes can verify their split_bins index THESE cuts
    # (a Booster continued on a different DMatrix must not reuse stale bins)
    token: int = dataclasses.field(default_factory=_fresh_cuts_token)

    @property
    def n_features(self) -> int:
        return len(self.cut_ptrs) - 1

    @property
    def total_bins(self) -> int:
        return int(self.cut_ptrs[-1])

    def n_bins(self, f: int) -> int:
        return int(self.cut_ptrs[f + 1] - self.cut_ptrs[f])

    @property
    def max_n_bins(self) -> int:
        return int(np.max(np.diff(self.cut_ptrs))) if self.n_features else 0

    def feature_cuts(self, f: int) -> np.ndarray:
        return self.cut_values[self.cut_ptrs[f] : self.cut_ptrs[f + 1]]

    def padded(self, width: Optional[int] = None) -> np.ndarray:
        """Dense (F, B) cut matrix padded with +inf — the jit-friendly layout.

        Padded slots never win a split because their histogram mass is zero and
        the evaluator masks bins >= n_bins(f).
        """
        B = width or self.max_n_bins
        out = np.full((self.n_features, B), np.inf, dtype=np.float32)
        for f in range(self.n_features):
            seg = self.feature_cuts(f)
            out[f, : len(seg)] = seg
        return out

    def n_bins_array(self) -> np.ndarray:
        return np.diff(self.cut_ptrs).astype(np.int32)


def _final_cut(vmax: float) -> float:
    # Reference hist_util.cc appends max + small delta so max lands in the last bin.
    return float(vmax + (abs(vmax) * 1e-2 if vmax != 0.0 else 1e-5) + 1e-5)


def cuts_from_quantile_grid(
    grid: np.ndarray, n_valid: np.ndarray, vmax: np.ndarray, vmin: np.ndarray
) -> HistogramCuts:
    """Finalize ragged cuts from a dense (F, Q) quantile grid.

    grid[f, q] is the q-th quantile candidate of feature f (rows with
    n_valid[f]==0 are all-NaN features).  Dedupes per feature and appends the
    open upper bound.
    """
    F, _ = grid.shape
    ptrs = [0]
    values: List[np.ndarray] = []
    mins = np.empty(F, dtype=np.float32)
    for f in range(F):
        if n_valid[f] == 0:
            seg = np.array([1e-5], dtype=np.float32)  # single catch-all bin
            mins[f] = -1e-5
        else:
            cand = np.unique(grid[f][np.isfinite(grid[f])])
            # drop candidates that equal the running max; the final cut covers them
            last = _final_cut(float(vmax[f]))
            cand = cand[cand < last]
            # candidates must exceed the feature min so bin 0 is non-empty-able
            seg = np.append(cand[cand > vmin[f]], np.float32(last)).astype(np.float32)
            mins[f] = vmin[f] - (abs(vmin[f]) * 1e-2 if vmin[f] != 0 else 1e-5)
        values.append(seg)
        ptrs.append(ptrs[-1] + len(seg))
    return HistogramCuts(
        cut_ptrs=np.asarray(ptrs, dtype=np.int32),
        cut_values=np.concatenate(values).astype(np.float32) if values else np.zeros(0, np.float32),
        min_vals=mins,
    )


def categorical_cuts(n_cats: int) -> np.ndarray:
    """Identity cuts for a categorical feature: code c lands in bin c
    (cuts [1..n_cats]; searchsorted side='right' of code c gives c)."""
    return np.arange(1, max(n_cats, 1) + 1, dtype=np.float32)


def _assemble_cuts(F: int, max_bin: int, cat_n_cats, num_seg) -> HistogramCuts:
    """Stitch per-feature cut segments: identity cuts for categorical
    features (cat_n_cats: {feature -> n_cats}), ``num_seg(f) -> (seg, min)``
    for numeric ones.  Shared by every sketch flavour."""
    ptrs, values = [0], []
    mins = np.zeros(F, np.float32)
    for f in range(F):
        if f in cat_n_cats:
            n_cats = cat_n_cats[f]
            if n_cats > max_bin:
                raise ValueError(
                    f"categorical feature {f} has {n_cats} categories; "
                    f"raise max_bin (currently {max_bin})")
            seg = categorical_cuts(n_cats)
            mins[f] = -1e-5
        else:
            seg, mins[f] = num_seg(f)
        values.append(seg)
        ptrs.append(ptrs[-1] + len(seg))
    return HistogramCuts(
        np.asarray(ptrs, np.int32),
        np.concatenate(values).astype(np.float32) if values else np.zeros(0, np.float32),
        mins)


def sketch_dense(
    X,
    max_bin: int,
    weights: Optional[np.ndarray] = None,
    use_device: bool = True,
    cat_mask: Optional[np.ndarray] = None,
) -> HistogramCuts:
    """Build HistogramCuts from a dense (R, F) float matrix with NaN = missing.

    Device path: one jnp.sort per feature column block + a gather at quantile
    positions; only the (F, max_bin) grid is pulled back to host (the analogue
    of the reference's device sketch returning pruned summaries,
    src/common/hist_util.cuh:213 DeviceSketch).
    Weighted data falls back to a host weighted-CDF quantile (reference:
    WQSketch handles weights natively).
    """
    Xn = np.asarray(X, dtype=np.float32) if not hasattr(X, "devices") else X
    R, F = Xn.shape
    n_cand = max(max_bin - 1, 1)

    if cat_mask is not None and np.any(cat_mask):
        # categorical columns get identity cuts; only numeric columns are
        # sketched (reference: CatContainer ordinal encoding, cat_container)
        Xh = np.asarray(Xn)
        num_idx = np.nonzero(~cat_mask)[0]
        base = (sketch_dense(Xh[:, num_idx], max_bin, weights=weights,
                             use_device=use_device)
                if len(num_idx) else None)
        cat_n_cats = {}
        for f in np.nonzero(cat_mask)[0]:
            col = Xh[:, f]
            col = col[~np.isnan(col)]
            cat_n_cats[int(f)] = int(col.max()) + 1 if len(col) else 1
        num_pos = {int(f): i for i, f in enumerate(num_idx)}
        return _assemble_cuts(
            F, max_bin, cat_n_cats,
            lambda f: (base.feature_cuts(num_pos[f]), base.min_vals[num_pos[f]]))

    if weights is not None:
        return _sketch_weighted_host(np.asarray(Xn, dtype=np.float32), max_bin, np.asarray(weights))

    if use_device and R * F > 0:
        import jax
        import jax.numpy as jnp

        import os as _os

        if (jax.default_backend() == "cpu"
                and not _os.environ.get("XTB_FORCE_DEVICE_SKETCH")):
            # XLA's CPU sort is ~20x slower than numpy's (measured 27s vs
            # 1.7s for 1M x 28); the host grid is exact and fast there.
            # XTB_FORCE_DEVICE_SKETCH=1 keeps the accelerator code path
            # CI-covered on the CPU backend (tests/test_basic.py).
            return _sketch_weighted_host(np.asarray(Xn, np.float32),
                                         max_bin, None)

        Xd = jnp.asarray(Xn, dtype=jnp.float32)
        # accelerator sorts are bitonic (O(R log^2 R) HBM passes): above
        # ~2^19 rows a deterministic stride subsample makes the sketch
        # O(sample) with quantile error O(1/sqrt(sample)) — well inside the
        # binning tolerance (the reference's streaming sketch is likewise
        # eps-approximate, src/common/quantile.h); min/max stay exact via
        # full-data reductions below so the value range never clips
        SAMPLE = 1 << 19
        if R > SAMPLE:
            stride = (R + SAMPLE - 1) // SAMPLE
            Xs = Xd[::stride]
        else:
            Xs = Xd
        sortd = jnp.sort(Xs, axis=0)  # NaNs sort to the end
        nvalid = jnp.sum(~jnp.isnan(Xs), axis=0)  # (F,) of the sample
        # quantile candidate ranks: ceil(i/ncand * nvalid) - style positions
        qs = (jnp.arange(1, n_cand + 1, dtype=jnp.float32) / (n_cand + 1))
        # inverted-CDF ranks: ceil(q*n) - 1 (matches np.quantile inverted_cdf
        # and the native streaming summary, so every sketch path agrees)
        pos = jnp.clip(
            jnp.ceil(qs[None, :] * nvalid[:, None].astype(jnp.float32)).astype(jnp.int32) - 1,
            0, jnp.maximum(nvalid[:, None] - 1, 0))
        grid = jnp.take_along_axis(sortd.T, pos, axis=1)  # (F, n_cand)
        # exact extremes + true valid counts from the FULL data (cheap
        # reductions), so sampling cannot clip the value range or skew the
        # distributed merge's mass weighting
        nvalid_full = jnp.sum(~jnp.isnan(Xd), axis=0)
        vmax = jnp.nanmax(Xd, axis=0, initial=-jnp.inf)
        vmin = jnp.nanmin(Xd, axis=0, initial=jnp.inf)
        grid_h = np.asarray(grid)
        nvalid_h = np.asarray(nvalid_full)
        vmax_h = np.where(nvalid_h > 0, np.asarray(vmax), 0.0)
        vmin_h = np.where(nvalid_h > 0, np.asarray(vmin), 0.0)
        grid_h = np.where(np.isnan(grid_h), np.inf, grid_h)
        return cuts_from_quantile_grid(grid_h, nvalid_h, vmax_h, vmin_h)

    return _sketch_weighted_host(Xn, max_bin, None)


def _sketch_weighted_host(X: np.ndarray, max_bin: int, w: Optional[np.ndarray]) -> HistogramCuts:
    return cuts_from_quantile_grid(*_host_grid(X, max_bin, w)[:4])


def _host_grid(X: np.ndarray, max_bin: int, w: Optional[np.ndarray]):
    """Per-feature quantile candidate grid (F, max_bin-1) + stats — the
    fixed-size 'summary' exchanged by the distributed sketch merge.
    Returns (grid, nvalid, vmax, vmin, mass); mass is the per-feature total
    sample weight (== nvalid when unweighted), the quantity that weights this
    shard's candidates in the merge."""
    R, F = X.shape
    n_cand = max(max_bin - 1, 1)
    grid = np.full((F, n_cand), np.inf, dtype=np.float32)
    nvalid = np.zeros(F, dtype=np.int64)
    vmax = np.zeros(F, dtype=np.float32)
    vmin = np.zeros(F, dtype=np.float32)
    qs = np.arange(1, n_cand + 1, dtype=np.float64) / (n_cand + 1)
    if w is None:
        if R == 0:
            # empty shard: all-inf grid + zero counts, what the
            # distributed merge expects from a contribution-free rank
            return grid, nvalid, vmax, vmin, nvalid.astype(np.float64)
        # one whole-matrix sort (NaNs sort last) + rank gather — the same
        # inverted-CDF positions as the device path, ~20x faster than
        # per-column np.quantile at wide F
        sortd = np.sort(X, axis=0)
        nvalid[:] = np.sum(~np.isnan(X), axis=0)
        pos = np.clip(
            np.ceil(qs[None, :] * nvalid[:, None]).astype(np.int64) - 1,
            0, np.maximum(nvalid[:, None] - 1, 0))
        got = np.take_along_axis(sortd.T, pos, axis=1).astype(np.float32)
        has = nvalid > 0
        grid[has] = got[has]
        vmax[has] = np.take_along_axis(
            sortd.T, np.maximum(nvalid[:, None] - 1, 0), axis=1)[has, 0]
        vmin[has] = sortd[0][has]
        return grid, nvalid, vmax, vmin, nvalid.astype(np.float64)
    for f in range(F):
        col = X[:, f]
        mask = ~np.isnan(col)
        vals = col[mask]
        nvalid[f] = len(vals)
        if len(vals) == 0:
            continue
        vmax[f] = vals.max()
        vmin[f] = vals.min()
        if w is not None:
            wf = w[mask].astype(np.float64)
            order = np.argsort(vals, kind="stable")
            sv, sw = vals[order], wf[order]
            cdf = np.cumsum(sw)
            tot = cdf[-1]
            if tot <= 0:
                grid[f] = np.quantile(vals, qs, method="inverted_cdf").astype(np.float32)
            else:
                idx = np.searchsorted(cdf, qs * tot, side="left")
                grid[f] = sv[np.clip(idx, 0, len(sv) - 1)].astype(np.float32)
    # only the weighted path reaches here (w is None returned early)
    wq = np.asarray(w, np.float64)
    mass = np.array([wq[~np.isnan(X[:, f])].sum() for f in range(F)])
    return grid, nvalid, vmax, vmin, mass


def merge_quantile_grids(grids: np.ndarray, nvalids: np.ndarray,
                         vmaxs: np.ndarray, vmins: np.ndarray,
                         max_bin: int,
                         masses: Optional[np.ndarray] = None) -> HistogramCuts:
    """Merge per-worker quantile grids into shared cuts.

    The TPU-shaped analogue of the reference's summary allreduce
    (src/common/quantile.cc:397-442 SketchContainer::AllReduce): instead of
    merging GK summaries with rank bounds, every worker contributes a
    fixed-size quantile grid whose k-th worker candidates each carry an equal
    share of that worker's total sample-weight mass (masses[k,f], == nvalid
    when unweighted); the merged cuts are inverted-CDF quantiles of the
    weighted union.  Deterministic given the gathered inputs, so every worker
    computes bitwise-identical cuts.

    grids: (W, F, Q), nvalids/masses: (W, F), vmaxs/vmins: (W, F).
    """
    W, F, Q = grids.shape
    if masses is None:
        masses = nvalids.astype(np.float64)
    n_cand = max(max_bin - 1, 1)
    qs = np.arange(1, n_cand + 1, dtype=np.float64) / (n_cand + 1)
    grid = np.full((F, n_cand), np.inf, dtype=np.float32)
    nvalid = nvalids.sum(axis=0).astype(np.int64)
    vmax = np.zeros(F, dtype=np.float32)
    vmin = np.zeros(F, dtype=np.float32)
    for f in range(F):
        has = nvalids[:, f] > 0
        if not has.any():
            continue
        vmax[f] = vmaxs[has, f].max()
        vmin[f] = vmins[has, f].min()
        cand_list, w_list = [], []
        for k in np.nonzero(has)[0]:
            c = grids[k, f]
            c = c[np.isfinite(c)]
            if len(c) == 0:
                continue
            cand_list.append(c.astype(np.float64))
            w_list.append(np.full(len(c), masses[k, f] / len(c), np.float64))
        cand = np.concatenate(cand_list)
        wts = np.concatenate(w_list)
        order = np.argsort(cand, kind="stable")
        sv, sw = cand[order], wts[order]
        cdf = np.cumsum(sw)
        idx = np.searchsorted(cdf, qs * cdf[-1], side="left")
        grid[f] = sv[np.clip(idx, 0, len(sv) - 1)].astype(np.float32)
    return cuts_from_quantile_grid(grid, nvalid, vmax, vmin)


def _pack_contrib(grid: np.ndarray, nvalid: np.ndarray, vmax: np.ndarray,
                  vmin: np.ndarray, mass: np.ndarray) -> np.ndarray:
    """One page's sketch contribution as a single (F, Q+4) f64 block, so the
    whole per-rank summary crosses the collective in ONE ragged gather.
    f32 grid values and int64 counts round-trip exactly through f64."""
    F, Q = grid.shape
    out = np.empty((F, Q + 4), np.float64)
    out[:, :Q] = grid
    out[:, Q] = nvalid
    out[:, Q + 1] = vmax
    out[:, Q + 2] = vmin
    out[:, Q + 3] = mass
    return out


class StreamingSketch:
    """Page-at-a-time (distributed) quantile sketch.

    The out-of-core analogue of :func:`sketch_distributed`: instead of one
    grid from a materialized shard, every pushed page contributes one
    fixed-size summary — exactly what :func:`_host_grid` produces for that
    page — and ``finalize()`` merges ALL page contributions (across every
    rank when ``distributed=True``) through :func:`merge_quantile_grids`,
    so cuts never require the full matrix resident.

    Pinned contract (tests/test_extmem.py sketch-parity fuzz): **the page
    is the atomic sketch unit, and the merge is a pure function of the
    multiset of page contributions.**  Candidates are value-sorted inside
    :func:`merge_quantile_grids` and a tied value is selected by value, not
    position, so the merged cuts are bitwise-identical however the pages
    are grouped onto ranks (world 1/2/4/...) and in whatever order they
    are pushed — and equal to the one-shot :func:`sketch_distributed`
    where each page is one rank's whole shard.  Per-rank memory is
    O(pages x F x max_bin); the summary allreduce is one ragged gather of
    the packed page blocks plus (with categoricals) one MAX-allreduce.
    """

    def __init__(self, n_features: int, max_bin: int,
                 cat_mask: Optional[np.ndarray] = None) -> None:
        self.n_features = int(n_features)
        self.max_bin = int(max_bin)
        cm = None
        if cat_mask is not None and np.any(cat_mask):
            cm = np.asarray(cat_mask, bool)
            if len(cm) != self.n_features:
                raise ValueError("cat_mask length != n_features")
        self.cat_mask = cm
        self._contribs: List[np.ndarray] = []
        self._cat_max = np.full(self.n_features, -1.0, np.float32)

    @property
    def n_cand(self) -> int:
        return max(self.max_bin - 1, 1)

    @property
    def n_pages(self) -> int:
        return len(self._contribs)

    def push(self, X, weights: Optional[np.ndarray] = None) -> None:
        """Fold one dense (R, F) page (NaN = missing) into the sketch."""
        Xh = np.asarray(X, dtype=np.float32)
        if Xh.shape[1] != self.n_features:
            raise ValueError(
                f"page has {Xh.shape[1]} features, sketch expects "
                f"{self.n_features}")
        w = None if weights is None else np.asarray(weights)
        if self.cat_mask is None:
            self._contribs.append(_pack_contrib(*_host_grid(
                Xh, self.max_bin, w)))
            return
        # categorical columns never enter the numeric sort: their cuts come
        # from the global category max (a nanmax, not a quantile grid), and
        # their rows in the packed contribution stay the empty-feature
        # sentinel (inf grid / zero stats) the merge ignores
        cat = self.cat_mask
        for f in np.nonzero(cat)[0]:
            col = Xh[:, f]
            col = col[~np.isnan(col)]
            if len(col):
                self._cat_max[f] = max(self._cat_max[f], col.max())
        F, Q = self.n_features, self.n_cand
        grid = np.full((F, Q), np.inf, np.float32)
        nvalid = np.zeros(F, np.int64)
        vmax = np.zeros(F, np.float32)
        vmin = np.zeros(F, np.float32)
        mass = np.zeros(F, np.float64)
        num_idx = np.nonzero(~cat)[0]
        if len(num_idx):
            g, nv, vx, vn, ms = _host_grid(Xh[:, num_idx], self.max_bin, w)
            grid[num_idx] = g
            nvalid[num_idx] = nv
            vmax[num_idx] = vx
            vmin[num_idx] = vn
            mass[num_idx] = ms
        self._contribs.append(_pack_contrib(grid, nvalid, vmax, vmin, mass))

    def push_csr(self, indptr, indices, values,
                 weights: Optional[np.ndarray] = None) -> None:
        """Fold one CSR page (implicit zeros = missing, matching
        :func:`sketch_csr`) without densifying it."""
        grid, nvalid, vmax, vmin, mass, cat_local = _csr_grid(
            np.asarray(indptr), np.asarray(indices), np.asarray(values),
            self.n_features, self.max_bin,
            None if weights is None else np.asarray(weights), self.cat_mask)
        np.maximum(self._cat_max, cat_local, out=self._cat_max)
        self._contribs.append(_pack_contrib(grid, nvalid, vmax, vmin, mass))

    def finalize(self, distributed: bool = False) -> HistogramCuts:
        """Merge every page contribution into shared cuts.

        ``distributed=True`` gathers all ranks' packed page blocks in one
        ragged allgather (every rank computes bitwise-identical cuts); a
        rank may hold any number of pages, including zero, as long as the
        job holds at least one page overall — but every rank must CALL
        finalize (``ExtMemQuantileDMatrix`` additionally requires one
        batch per rank, since it learns the feature count from it)."""
        from .. import collective

        F, Q = self.n_features, self.n_cand
        local = (np.stack(self._contribs) if self._contribs
                 else np.zeros((0, F, Q + 4), np.float64))
        cat_max = self._cat_max
        if distributed:
            flat = local.reshape(local.shape[0], F * (Q + 4))
            local = collective.allgather_ragged(flat).reshape(-1, F, Q + 4)
            if self.cat_mask is not None:
                cat_max = collective.allreduce(cat_max, collective.Op.MAX)
        if local.shape[0] == 0:
            raise ValueError("StreamingSketch.finalize: no pages pushed")
        base = merge_quantile_grids(
            local[:, :, :Q].astype(np.float32),
            local[:, :, Q].astype(np.int64),
            local[:, :, Q + 1].astype(np.float32),
            local[:, :, Q + 2].astype(np.float32),
            self.max_bin, masses=local[:, :, Q + 3])
        if self.cat_mask is None:
            return base
        cat_n_cats = {int(f): (int(cat_max[f]) + 1 if cat_max[f] >= 0 else 1)
                      for f in np.nonzero(self.cat_mask)[0]}
        return _assemble_cuts(
            F, self.max_bin, cat_n_cats,
            lambda f: (base.feature_cuts(f), base.min_vals[f]))


def sketch_distributed(X, max_bin: int, weights: Optional[np.ndarray] = None,
                       cat_mask: Optional[np.ndarray] = None) -> HistogramCuts:
    """Shared cuts across processes, each holding a row shard of X.

    One :class:`StreamingSketch` page per rank: local fixed-size grid ->
    one ragged gather -> deterministic merge; categorical features take
    identity cuts sized by the global category max."""
    Xh = np.asarray(X, dtype=np.float32)
    sk = StreamingSketch(Xh.shape[1], max_bin, cat_mask=cat_mask)
    sk.push(Xh, weights=weights)
    return sk.finalize(distributed=True)


def _csr_grid(indptr, indices, values, n_features: int, max_bin: int,
              weights: Optional[np.ndarray],
              cat_mask: Optional[np.ndarray]):
    """Per-feature quantile grid + stats of one CSR page — the CSR twin of
    :func:`_host_grid`, shared by :func:`sketch_csr` and
    :meth:`StreamingSketch.push_csr`.  Returns (grid, nvalid, vmax, vmin,
    mass, cat_local_max); categorical columns are excluded from the
    numeric grid (nvalid stays 0) and report their max code instead."""
    R = len(indptr) - 1
    n_cand = max(max_bin - 1, 1)
    grid = np.full((n_features, n_cand), np.inf, dtype=np.float32)
    nvalid = np.zeros(n_features, dtype=np.int64)
    vmax = np.zeros(n_features, dtype=np.float32)
    vmin = np.zeros(n_features, dtype=np.float32)
    mass = np.zeros(n_features, dtype=np.float64)
    cat_local_max = np.full(n_features, -1.0, np.float32)
    qs = np.arange(1, n_cand + 1, dtype=np.float64) / (n_cand + 1)
    # bucket values per column
    order = np.argsort(indices, kind="stable")
    col_sorted = indices[order]
    val_sorted = values[order]
    starts = np.searchsorted(col_sorted, np.arange(n_features + 1))
    if weights is not None:
        row_of = np.repeat(np.arange(R), np.diff(indptr))[order]
    is_cat = np.zeros(n_features, bool) if cat_mask is None else np.asarray(cat_mask)
    for f in range(n_features):
        seg = val_sorted[starts[f] : starts[f + 1]].astype(np.float32)
        keep = ~np.isnan(seg)
        vals = seg[keep]
        if is_cat[f]:
            # NOTE: CSR categorical needs explicit storage — implicit zeros
            # are missing, so category 0 must be stored explicitly.
            # nvalid stays 0: cat features are excluded from the numeric
            # grid merge (their cuts come from the category max below)
            if len(vals):
                cat_local_max[f] = vals.max()
            continue
        nvalid[f] = len(vals)
        if len(vals) == 0:
            continue
        vmax[f], vmin[f] = vals.max(), vals.min()
        if weights is None:
            mass[f] = len(vals)
            grid[f] = np.quantile(vals, qs, method="inverted_cdf").astype(np.float32)
        else:
            wf = weights[row_of[starts[f] : starts[f + 1]]][keep].astype(np.float64)
            o = np.argsort(vals, kind="stable")
            sv, sw = vals[o], wf[o]
            cdf = np.cumsum(sw)
            mass[f] = cdf[-1]
            idx = np.searchsorted(cdf, qs * cdf[-1], side="left")
            grid[f] = sv[np.clip(idx, 0, len(sv) - 1)].astype(np.float32)
    return grid, nvalid, vmax, vmin, mass, cat_local_max


def sketch_csr(indptr, indices, values, n_features: int, max_bin: int,
               weights: Optional[np.ndarray] = None,
               cat_mask: Optional[np.ndarray] = None,
               distributed: bool = False) -> HistogramCuts:
    """Sketch a CSR matrix column-by-column on host (sparse ingest path).

    Implicit zeros in sparse input are treated as missing, matching the
    reference's sparse DMatrix semantics (only stored entries are sketched,
    src/common/hist_util.cc SketchOnDMatrix walks nonzeros).
    ``distributed=True``: this process holds a row shard — one
    :class:`StreamingSketch` page per rank, merged across processes
    without ever densifying the shard.
    """
    if distributed:
        sk = StreamingSketch(n_features, max_bin, cat_mask=cat_mask)
        sk.push_csr(indptr, indices, values, weights=weights)
        return sk.finalize(distributed=True)
    grid, nvalid, vmax, vmin, _mass, cat_local_max = _csr_grid(
        indptr, indices, values, n_features, max_bin, weights, cat_mask)
    base = cuts_from_quantile_grid(grid, nvalid, vmax, vmin)
    is_cat = np.zeros(n_features, bool) if cat_mask is None else np.asarray(cat_mask)
    if not is_cat.any():
        return base
    cat_n_cats = {int(f): (int(cat_local_max[f]) + 1 if cat_local_max[f] >= 0 else 1)
                  for f in np.nonzero(is_cat)[0]}
    return _assemble_cuts(
        n_features, max_bin, cat_n_cats,
        lambda f: (base.feature_cuts(f), base.min_vals[f]))
