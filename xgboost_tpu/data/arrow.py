"""Arrow columnar ingestion: pyarrow Table/RecordBatch -> column-major numpy.

The reference ingests Arrow through a zero-copy columnar adapter
(src/data/adapter.h:437 ColumnarAdapter; python-package/xgboost/data.py
arrow dispatch).  Here the contract is narrower but the semantics match:

- numeric columns copy to float32 with nulls -> NaN (the missing sentinel
  of the whole pipeline);
- dictionary-encoded columns are categoricals: the physical CODES feed the
  tree (feature_type "c") and the dictionary VALUES persist on the DMatrix
  for train->inference recode (reference: src/encoder/ordinal.h Recode,
  exported via ``DMatrix.get_categories``/``Booster.get_categories``).

DMatrix construction dispatches here (``_to_numpy_2d`` in dmatrix.py) for
anything whose module root is ``pyarrow``; pyarrow itself is imported only
inside that dispatch, so the dependency stays optional.
"""
from __future__ import annotations

from typing import Any

import numpy as np


def is_arrow(data: Any) -> bool:
    """True for pyarrow Table / RecordBatch (no pyarrow import needed)."""
    return type(data).__module__.split(".")[0] == "pyarrow" and hasattr(
        data, "schema")


def arrow_to_columnar(data: Any, missing: float, normalize_dense):
    """Convert an arrow Table/RecordBatch to the dmatrix payload triple
    ``(("dense", array, cat_categories), feature_names, feature_types)``.

    ``normalize_dense`` is dmatrix.py's shared sentinel->NaN normalizer so
    arrow rows obey exactly the host-ingest missing semantics (the custom
    ``missing`` value applies to numeric columns only — categorical codes
    are unrelated to the user's sentinel)."""
    import pyarrow as pa

    feature_names = [str(c) for c in data.schema.names]
    feature_types = []
    cols = []
    cat_categories = {}
    for fi, name in enumerate(data.schema.names):
        col = data.column(name)
        if isinstance(col, pa.ChunkedArray):
            col = col.combine_chunks()
        if pa.types.is_dictionary(col.type):
            # dictionary-encoded = categorical: physical codes train the
            # tree, the dictionary VALUES persist for train->infer recode
            cat_categories[fi] = [v.as_py() for v in col.dictionary]
            codes = col.indices.to_numpy(zero_copy_only=False).astype(
                np.float32)
            if col.null_count:
                codes[np.asarray(col.is_null())] = np.nan
            cols.append(codes)
            feature_types.append("c")
        else:
            vals = col.to_numpy(zero_copy_only=False).astype(np.float32)
            if col.null_count:
                vals[np.asarray(col.is_null())] = np.nan
            cols.append(vals)
            feature_types.append(
                "q" if pa.types.is_floating(col.type) else "int")
    arr = (np.stack(cols, axis=1) if cols
           else np.zeros((data.num_rows, 0), np.float32))
    return (("dense",
             normalize_dense(arr, missing, np, feature_types),
             cat_categories),
            feature_names, feature_types)
