"""Arrow columnar ingestion: pyarrow Table/RecordBatch -> column-major numpy.

The reference ingests Arrow through a zero-copy columnar adapter
(src/data/adapter.h:437 ColumnarAdapter; python-package/xgboost/data.py
arrow dispatch).  Here the contract is narrower but the semantics match:

- numeric columns copy to float32 with nulls -> NaN (the missing sentinel
  of the whole pipeline);
- dictionary-encoded columns are categoricals: the physical CODES feed the
  tree (feature_type "c") and the dictionary VALUES persist on the DMatrix
  for train->inference recode (reference: src/encoder/ordinal.h Recode,
  exported via ``DMatrix.get_categories``/``Booster.get_categories``).

DMatrix construction dispatches here (``_to_numpy_2d`` in dmatrix.py) for
anything whose module root is ``pyarrow``; pyarrow itself is imported only
inside that dispatch, so the dependency stays optional.
"""
from __future__ import annotations

from typing import Any

import numpy as np


def is_arrow(data: Any) -> bool:
    """True for pyarrow Table / RecordBatch (no pyarrow import needed)."""
    return type(data).__module__.split(".")[0] == "pyarrow" and hasattr(
        data, "schema")


def arrow_to_columnar(data: Any, missing: float, normalize_dense):
    """Convert an arrow Table/RecordBatch to the dmatrix payload triple
    ``(("dense", array, cat_categories), feature_names, feature_types)``.

    ``normalize_dense`` is dmatrix.py's shared sentinel->NaN normalizer so
    arrow rows obey exactly the host-ingest missing semantics (the custom
    ``missing`` value applies to numeric columns only — categorical codes
    are unrelated to the user's sentinel)."""
    import pyarrow as pa

    feature_names = [str(c) for c in data.schema.names]
    feature_types = []
    cols = []
    cat_categories = {}
    for fi, name in enumerate(data.schema.names):
        col = data.column(name)
        if isinstance(col, pa.ChunkedArray):
            col = col.combine_chunks()
        if pa.types.is_dictionary(col.type):
            # dictionary-encoded = categorical: physical codes train the
            # tree, the dictionary VALUES persist for train->infer recode
            cat_categories[fi] = [v.as_py() for v in col.dictionary]
            codes = col.indices.to_numpy(zero_copy_only=False).astype(
                np.float32)
            if col.null_count:
                codes[np.asarray(col.is_null())] = np.nan
            cols.append(codes)
            feature_types.append("c")
        else:
            vals = col.to_numpy(zero_copy_only=False).astype(np.float32)
            if col.null_count:
                vals[np.asarray(col.is_null())] = np.nan
            cols.append(vals)
            feature_types.append(
                "q" if pa.types.is_floating(col.type) else "int")
    arr = (np.stack(cols, axis=1) if cols
           else np.zeros((data.num_rows, 0), np.float32))
    return (("dense",
             normalize_dense(arr, missing, np, feature_types),
             cat_categories),
            feature_names, feature_types)


def ipc_batch_to_dense(payload) -> np.ndarray:
    """Arrow IPC stream bytes -> (R, F) float32 matrix, reading straight
    off the IPC buffer (the fleet replica's request-path decoder).

    Zero-copy fast path: every column float32 with no nulls — each column
    becomes a ``to_numpy(zero_copy_only=True)`` view over the received
    buffer and the single copy on the whole request path is the final
    columnar->row-major ``np.stack`` at the kernel boundary (the same
    layout transform the in-process engine pays in ``_as_batch``).
    Columns of other numeric types or with nulls take the copying
    ``astype``/NaN-fill route with the exact semantics of
    :func:`arrow_to_columnar` numeric ingestion (nulls -> NaN).
    """
    import pyarrow as pa

    with pa.ipc.open_stream(pa.py_buffer(payload)) as reader:
        table = reader.read_all()
    batch = table.combine_chunks()
    cols = []
    for ci in range(batch.num_columns):
        col = batch.column(ci)
        if isinstance(col, pa.ChunkedArray):
            col = (col.combine_chunks() if col.num_chunks != 1
                   else col.chunk(0))
        if pa.types.is_dictionary(col.type):
            # serving-time category recode needs the train-time dictionary
            # (snapshot.host_dense_recoded); on the wire, send the CODES
            raise ValueError(
                "dictionary-encoded columns are not accepted on the fleet "
                "request path: recode to training category codes client-"
                "side and send the numeric codes (Booster.get_categories "
                "exports the train-time dictionaries)")
        if pa.types.is_float32(col.type) and col.null_count == 0:
            cols.append(col.to_numpy(zero_copy_only=True))
        else:
            vals = col.to_numpy(zero_copy_only=False).astype(np.float32)
            if col.null_count:
                vals[np.asarray(col.is_null())] = np.nan
            cols.append(vals)
    if not cols:
        return np.zeros((batch.num_rows, 0), np.float32)
    return np.stack(cols, axis=1)
