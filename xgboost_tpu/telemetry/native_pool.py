"""Bridge: native ParallelFor pool counters -> the telemetry registry.

The pool (native/xtb_kernels.h XtbThreadPool) lives in C++ — one instance
per loaded kernel library — and tracks per-kernel region counts, busy
nanoseconds, and pre-bucketed per-region busy-second histograms whose
bounds equal ``registry.DEFAULT_BUCKETS`` exactly.  ``sync()`` reads those
counters through the pool C ABI (utils/native.py ``pool_stats``) and folds
the DELTAS since the previous sync into three registry families:

- ``xtb_native_threads`` (gauge) — configured pool width;
- ``xtb_native_parallel_regions_total{kernel}`` (counter) — multi-shard
  parallel regions dispatched (inline/single-shard runs are not regions);
- ``xtb_native_busy_seconds{kernel}`` (histogram) — per-region busy seconds
  summed over the participating threads;
- ``xtb_native_kernel_cycles_total{kernel}`` /
  ``xtb_native_kernel_bytes_total{kernel}`` (counters) — cycle counts
  (rdtsc/cntvct) and modeled bytes touched from the per-invocation
  XtbKernelPerf scopes, the inputs to roofline attribution
  (scripts/bench_roofline.py).

Metrics appear only after the first ``sync()``: the pool is C++ and cannot
push into the Python registry itself, so scrape endpoints and snapshot
readers call ``sync()`` first (the serving example in
docs/observability.md does).
"""
from __future__ import annotations

import threading
from typing import Dict, Tuple

from ..utils import native
from .registry import get_registry

_lock = threading.Lock()
# per-kernel last-seen (regions, busy_ns, buckets) so repeated syncs fold
# only the delta into the monotone registry families
_seen: Dict[str, Tuple[int, int, Tuple[int, ...]]] = {}
# per-kernel last-seen (cycles, bytes) for the perf-counter families
_seen_perf: Dict[str, Tuple[int, int]] = {}


def sync() -> dict:
    """Fold fresh pool counters into the registry; returns the raw
    aggregated ``native.pool_stats()`` snapshot for convenience."""
    stats = native.pool_stats()
    reg = get_registry()
    reg.gauge("xtb_native_threads",
              "configured native ParallelFor pool width").set(
                  stats["nthread"])
    regions = reg.counter(
        "xtb_native_parallel_regions_total",
        "multi-shard parallel regions dispatched by the native pool",
        ("kernel",))
    busy = reg.histogram(
        "xtb_native_busy_seconds",
        "per-region busy seconds (summed over participating threads)",
        ("kernel",))
    cycles = reg.counter(
        "xtb_native_kernel_cycles_total",
        "cpu cycles spent inside native kernel invocations (rdtsc)",
        ("kernel",))
    nbytes = reg.counter(
        "xtb_native_kernel_bytes_total",
        "modeled bytes touched by native kernel invocations",
        ("kernel",))
    with _lock:
        for name, k in stats["kernels"].items():
            prev = _seen.get(name, (0, 0, tuple([0] * len(k["buckets"]))))
            d_regions = k["regions"] - prev[0]
            d_busy_ns = max(k["busy_ns"] - prev[1], 0)
            d_buckets = [max(b - p, 0)
                         for b, p in zip(k["buckets"], prev[2])]
            # the C counters are per-slot atomics, not a snapshot: a read
            # concurrent with record() can tear across slots.  Deriving the
            # histogram count FROM the bucket deltas keeps the Prometheus
            # invariant (+Inf cumulative == _count) by construction, and a
            # torn region only shifts when an increment is folded, never
            # whether
            d_count = sum(d_buckets)
            if d_regions > 0:
                regions.labels(name).inc(d_regions)
            if d_count > 0:
                busy.labels(name).merge_bucketed(
                    d_buckets, d_busy_ns * 1e-9, d_count)
            _seen[name] = (k["regions"], k["busy_ns"],
                           tuple(k["buckets"]))
            pprev = _seen_perf.get(name, (0, 0))
            d_cycles = max(int(k.get("cycles", 0)) - pprev[0], 0)
            d_bytes = max(int(k.get("bytes", 0)) - pprev[1], 0)
            if d_cycles > 0:
                cycles.labels(name).inc(d_cycles)
            if d_bytes > 0:
                nbytes.labels(name).inc(d_bytes)
            _seen_perf[name] = (int(k.get("cycles", 0)),
                                int(k.get("bytes", 0)))
    return stats
