"""Metric-catalog help text, sourced from docs/observability.md.

The docs catalog table is the operator-facing contract for every
``xtb_*`` family (xtblint XTB4xx keeps it in sync with the code).  Some
families are registered with an empty ``help`` string — e.g. lazily
created counters where the call site keeps the line short — and their
``# HELP`` exposition line would silently vanish.  This module parses the
catalog table once and hands ``render_prometheus()`` the documented
meaning as the fallback help text, so the scrape output and the docs
describe every series with the same words.

Best-effort by design: when the docs tree is not present next to the
package (a bare install), ``help_for`` returns ``""`` and exposition
simply omits the HELP line, exactly as before.
"""
from __future__ import annotations

import os
import re
from typing import Dict, Optional

__all__ = ["help_for", "catalog", "catalog_path"]

_NAME_RE = re.compile(r"^xtb_[a-z0-9_]+$")
_cache: Optional[Dict[str, str]] = None


def catalog_path() -> str:
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, "docs", "observability.md")


def _parse(text: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("| `xtb_"):
            continue
        cells = [c.strip() for c in line.strip("|").split("|")]
        if len(cells) < 2:
            continue
        name = cells[0].strip("`").strip()
        if not _NAME_RE.match(name):
            continue
        # the MEANING column is last by convention; strip markdown
        # emphasis but keep backticked cross-references readable
        meaning = cells[-1].replace("**", "").strip()
        if meaning:
            out.setdefault(name, meaning)
    return out


def catalog() -> Dict[str, str]:
    """{metric family name: documented meaning} from the docs catalog
    table (empty when the docs are not shipped alongside the package)."""
    global _cache
    if _cache is None:
        try:
            with open(catalog_path(), "r", encoding="utf-8") as fh:
                _cache = _parse(fh.read())
        except OSError:
            _cache = {}
    return _cache


def help_for(name: str) -> str:
    return catalog().get(name, "")
