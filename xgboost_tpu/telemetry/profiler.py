"""Always-on sampling wall profiler: folded stacks from every process.

Role model: the Linux `perf` + FlameGraph collapsed-stack workflow
(Gregg's `stackcollapse` format: one line per unique stack,
``frame;frame;frame count``), built from pure-Python wall sampling so it
works identically in the driver, fleet replicas, tracker relays, and
training workers — no ptrace, no signals, no native unwinder.

A single daemon thread wakes ``XGBOOST_TPU_PROF_HZ`` times per second
(default :data:`DEFAULT_HZ`; ``0`` disables) and snapshots every live
thread's Python stack via ``sys._current_frames()``.  Each observed
stack folds into an in-memory ``{stack_key: count}`` dict whose keys are
root-first ``thread;module:func;module:func;...`` strings.  At a few Hz
the cost is a handful of microseconds per tick — the BENCH_OBS ≤5%
overhead gate runs with the profiler armed (scripts/bench_obs.py), and
training output is bitwise-identical with the profiler on or off
(tests/test_profiler.py) because sampling only ever *reads* frames.

Shipping rides the existing telemetry channels:
:func:`~xgboost_tpu.telemetry.distributed.snapshot_payload` attaches
:func:`folded_snapshot` under the ``"profile"`` key, so fleet replicas
(wire ``op="telemetry"`` frames) and tracker-mode workers (``cmd=
"telemetry"``) deliver their folded stacks to the driver without new
sockets.  The driver merges them — each stack prefixed with its source
label — into one flame view: :func:`merged_folded` returns the combined
dict, :func:`render_folded` writes the collapsed-stack file any
FlameGraph tool consumes plus a human-readable top-stacks text.

Clock discipline: pacing uses ``time.monotonic`` deadlines only
(xtblint XTB501 — no wall clock anywhere in the sampler).
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from .registry import get_registry

__all__ = [
    "ENV_HZ", "DEFAULT_HZ", "configured_hz", "start", "maybe_start",
    "stop", "running", "samples", "folded_snapshot", "merged_folded",
    "render_folded", "clear",
]

ENV_HZ = "XGBOOST_TPU_PROF_HZ"
DEFAULT_HZ = 5.0      # a few Hz: ~200ms between ticks, invisible in walls
_MAX_DEPTH = 64       # frames kept per stack (deepest dropped beyond this)
_MAX_STACKS = 4096    # distinct folded keys kept (overflow folds to a bin)
_OVERFLOW_KEY = "overflow;stacks_truncated"

_lock = threading.Lock()
_thread: Optional[threading.Thread] = None
_stop_evt: Optional[threading.Event] = None
_hz = 0.0
_label = ""
_samples = 0
_stacks: Dict[str, int] = {}


def _after_fork_child() -> None:
    # the sampler thread does not survive fork; drop the handle so the
    # child's next maybe_start() spins up its own (counts reset with the
    # fresh interpreter state the fork copied)
    global _thread, _stop_evt
    _lock.release()
    _thread = None
    _stop_evt = None


if hasattr(os, "register_at_fork"):  # pragma: no branch
    # hold the fold lock across fork so a child never inherits it locked
    os.register_at_fork(before=_lock.acquire,
                        after_in_parent=_lock.release,
                        after_in_child=_after_fork_child)


def configured_hz() -> float:
    """The env-configured sampling rate; unset/invalid -> DEFAULT_HZ."""
    raw = os.environ.get(ENV_HZ, "").strip()
    if not raw:
        return DEFAULT_HZ
    try:
        v = float(raw)
    except ValueError:
        return DEFAULT_HZ
    return max(0.0, v)


def _samples_counter():
    return get_registry().counter(
        "xtb_prof_samples_total",
        "Sampling-profiler ticks taken by this process")


def _frame_entry(code) -> str:
    base = os.path.basename(code.co_filename)
    if base.endswith(".py"):
        base = base[:-3]
    return f"{base}:{code.co_name}"


def _sample_once(own_ident: int) -> List[str]:
    """One tick: every live thread's stack as a folded key (root-first),
    excluding the sampler's own thread."""
    names = {t.ident: t.name for t in threading.enumerate()}
    keys: List[str] = []
    for ident, frame in sys._current_frames().items():
        if ident == own_ident:
            continue
        parts: List[str] = []
        f = frame
        while f is not None and len(parts) < _MAX_DEPTH:
            parts.append(_frame_entry(f.f_code))
            f = f.f_back
        parts.reverse()
        thread = names.get(ident) or f"tid-{ident}"
        keys.append(thread + ";" + ";".join(parts))
    return keys


def _run(stop_evt: threading.Event, period: float) -> None:
    global _samples
    counter = _samples_counter()
    own = threading.get_ident()
    next_t = time.monotonic() + period
    while not stop_evt.is_set():
        delay = next_t - time.monotonic()
        if delay > 0:
            if stop_evt.wait(delay):
                break
        else:
            # fell behind (suspended / heavily loaded): skip missed ticks
            # instead of bursting to catch up
            next_t = time.monotonic()
        next_t += period
        try:
            keys = _sample_once(own)
        except Exception:
            continue  # a racing thread teardown must not kill the sampler
        with _lock:
            _samples += 1
            for k in keys:
                if k in _stacks:
                    _stacks[k] += 1
                elif len(_stacks) < _MAX_STACKS:
                    _stacks[k] = 1
                else:
                    _stacks[_OVERFLOW_KEY] = _stacks.get(_OVERFLOW_KEY,
                                                         0) + 1
        counter.inc()


def start(hz: Optional[float] = None, label: str = "") -> bool:
    """Start the sampler (idempotent).  ``hz=None`` reads the env knob;
    ``hz<=0`` is a no-op returning False.  A second ``start`` while
    running just returns True — the first rate wins until :func:`stop`."""
    global _thread, _stop_evt, _hz, _label
    rate = configured_hz() if hz is None else max(0.0, float(hz))
    if rate <= 0.0:
        return False
    with _lock:
        if _thread is not None and _thread.is_alive():
            if label:
                _label = str(label)
            return True
        _hz = rate
        if label:
            _label = str(label)
        _stop_evt = threading.Event()
        _thread = threading.Thread(
            target=_run, args=(_stop_evt, 1.0 / rate), daemon=True,
            name="xtb-prof-sampler")
        _thread.start()
    return True


def maybe_start(label: str = "") -> bool:
    """The default-on entry point every long-lived loop calls (training
    rounds, fleet dispatcher, replica serve loop, tracker relay): starts
    at the env-configured rate unless disabled (``XGBOOST_TPU_PROF_HZ=0``)."""
    return start(None, label)


def stop(timeout: float = 2.0) -> None:
    """Stop the sampler (idempotent); accumulated stacks are kept."""
    global _thread, _stop_evt
    with _lock:
        t, evt = _thread, _stop_evt
        _thread, _stop_evt = None, None
    if evt is not None:
        evt.set()
    if t is not None and t.is_alive():
        t.join(timeout=timeout)


def running() -> bool:
    with _lock:
        return _thread is not None and _thread.is_alive()


def samples() -> int:
    with _lock:
        return _samples


def clear() -> None:
    """Drop accumulated stacks/counts (tests; the sampler keeps running)."""
    global _samples
    with _lock:
        _samples = 0
        _stacks.clear()


def folded_snapshot() -> Optional[dict]:
    """This process's profile as a JSON-serializable dict, or None when
    nothing was ever sampled (keeps idle payloads small).  Counts are
    cumulative since process start — receivers keep the latest snapshot
    per source, so re-ships overwrite rather than double-count."""
    with _lock:
        if _samples == 0 and not _stacks:
            return None
        return {"pid": os.getpid(), "label": _label, "hz": _hz,
                "samples": _samples, "stacks": dict(_stacks)}


# ---------------------------------------------------------------------------
# Driver-side merged flame view
# ---------------------------------------------------------------------------


def _source_tag(source: str, prof: dict) -> str:
    pid = prof.get("pid")
    return f"{source}/{pid}" if pid is not None else str(source)


def merged_folded(include_local: bool = True,
                  local_source: str = "driver") -> Dict[str, int]:
    """One folded-stack dict across every shipped profile plus (by
    default) this process's own: keys are ``source/pid;thread;frames...``
    so one flame graph separates processes at the root."""
    from . import distributed

    out: Dict[str, int] = {}
    rows: List[Tuple[str, dict]] = list(
        distributed.get_merged().profiles().items())
    if include_local:
        local = folded_snapshot()
        if local:
            rows.append((local_source, local))
    for source, prof in rows:
        if not isinstance(prof, dict):
            continue
        tag = _source_tag(source, prof)
        for stack, count in (prof.get("stacks") or {}).items():
            key = f"{tag};{stack}"
            out[key] = out.get(key, 0) + int(count)
    return out


def render_folded(path: Optional[str] = None, include_local: bool = True,
                  top: int = 20) -> str:
    """Render the merged flame view.  Returns a text report whose first
    section lists the ``top`` hottest stacks (count + leaf-to-root
    abbreviated) and whose second section is the raw collapsed-stack
    lines (``stack count``) — the exact stackcollapse format FlameGraph
    tools take.  ``path`` additionally writes just the collapsed lines
    to a file."""
    folded = merged_folded(include_local=include_local)
    ordered = sorted(folded.items(), key=lambda kv: (-kv[1], kv[0]))
    collapsed = "\n".join(f"{stack} {count}" for stack, count in ordered)
    if path is not None:
        with open(path, "w") as fh:
            fh.write(collapsed + ("\n" if collapsed else ""))
    total = sum(folded.values())
    lines = [f"# merged profile: {len(folded)} stacks, "
             f"{total} weighted samples"]
    for stack, count in ordered[:max(0, top)]:
        frames = stack.split(";")
        head = ";".join(frames[:2])          # source/pid;thread
        leaf = ";".join(frames[-3:]) if len(frames) > 5 else ";".join(
            frames[2:])
        pct = 100.0 * count / total if total else 0.0
        lines.append(f"{count:8d} {pct:5.1f}%  {head};...;{leaf}")
    lines.append("")
    lines.append(collapsed)
    return "\n".join(lines)
