"""Distributed observability plane: one merged view of a multi-process job.

PR 2's registry, spans, and Prometheus exposition are strictly
per-process, but the system is not: elastic training workers
(launcher.py + tracker relay), fleet replica processes (serving/fleet.py)
and lifecycle swaps all run their own interpreters, so a replica's
``xtb_serve_*`` series and a training rank's ``xtb_elastic_*`` counters
were invisible from the driver and vanished with the process.  This
module is the driver-side half of the fix:

- **Shipping** (the senders live on each process's EXISTING channel —
  no new sockets): fleet replicas append ``op="telemetry"`` wire frames
  on their dispatcher connection (serving/replica.py, periodically and
  at exit); tracker-mode training workers send ``cmd="telemetry"``
  messages on the persistent tracker channel
  (:meth:`~xgboost_tpu.tracker.TrackerClient.ship_telemetry`, driven by
  ``TelemetryCallback`` per round and by ``collective.finalize`` at
  exit).  Each payload is :func:`snapshot_payload`: the full registry
  snapshot plus the flight-recorder ring (flight.py).
- **MergedRegistry**: the driver ingests each process's latest snapshot
  under a source label (``replica0``, ``rank2``, ...).  Rendering emits
  BOTH views of every family: per-process samples relabeled with
  ``proc="<source>"``, and merged samples (no ``proc`` label) where
  counters and histogram buckets sum across processes and gauges sum too
  (documented in docs/observability.md's catalog scope column).  Dead
  processes keep their last snapshot — a SIGKILL'd replica's final
  numbers stay scrapeable.
- **Scrape endpoint**: a stdlib ``http.server`` ``/metrics`` endpoint
  (:func:`start_metrics_server`), opt-in via ``XGBOOST_TPU_METRICS_PORT``
  — started automatically by ``ServingFleet.start`` and
  ``launcher.run_distributed`` when the variable is set, or explicitly
  (``port=0`` picks an ephemeral port; read it back from ``server.port``).

The driver process's own registry is included as source ``driver`` so a
single scrape covers dispatcher-side series (``xtb_fleet_*``) alongside
the shipped ones.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

from . import flight
from .registry import (_escape_help, _escape_label, _exemplar_str, _fmt,
                       get_registry)

__all__ = [
    "snapshot_payload", "MergedRegistry", "get_merged",
    "MetricsServer", "start_metrics_server", "stop_metrics_server",
    "ship_to_tracker", "ship_interval", "ENV_PORT", "ENV_INTERVAL",
]

ENV_PORT = "XGBOOST_TPU_METRICS_PORT"
ENV_HOST = "XGBOOST_TPU_METRICS_HOST"
ENV_INTERVAL = "XGBOOST_TPU_TELEMETRY_INTERVAL"

PROC_LABEL = "proc"  # the relabel key per-process samples carry


def ship_interval() -> float:
    """Seconds between periodic snapshot ships (replicas + workers)."""
    try:
        return max(0.05, float(os.environ.get(ENV_INTERVAL, "2.0")))
    except ValueError:
        return 2.0


def _local_snapshot() -> dict:
    try:
        from . import native_pool

        native_pool.sync()  # fold fresh C-side pool counters first
    except Exception:
        pass
    return get_registry().snapshot()


def snapshot_payload() -> dict:
    """What one process ships: its full registry snapshot plus the
    flight-recorder ring (the driver dumps the ring when the process
    dies — the SIGKILL postmortem path) plus the watchdog's liveness
    progress markers (round, collective seq, page index — what the
    tracker's stall monitor compares between ships,
    docs/reliability.md "Coordinator failover & watchdog") plus, when
    the sampling profiler has run, its folded stacks (profiler.py —
    the driver merges them into one flame view)."""
    from ..reliability import watchdog
    from . import profiler

    payload = {"snapshot": _local_snapshot(), "flight": flight.events(),
               "progress": watchdog.markers(), "pid": os.getpid()}
    prof = profiler.folded_snapshot()
    if prof is not None:
        payload["profile"] = prof
    return payload


# ---------------------------------------------------------------------------
# Merged view
# ---------------------------------------------------------------------------


def _label_str(pairs: Sequence[Tuple[str, str]]) -> str:
    if not pairs:
        return ""
    return ("{" + ",".join(f'{n}="{_escape_label(v)}"' for n, v in pairs)
            + "}")


class MergedRegistry:
    """Driver-side union of per-process registry snapshots.

    ``ingest(source, snapshot)`` replaces that source's view (sources are
    retained until :meth:`clear` — death keeps the last snapshot).
    ``render_prometheus()`` emits one text exposition with per-process
    (``proc=``-labeled) and merged (unlabeled) samples per family;
    kind/label conflicts across sources keep the first-seen signature and
    skip the conflicting source's contribution for that family.

    Staleness: every ingest stamps a monotonic receive time; a source
    whose last snapshot is older than 3x :func:`ship_interval` renders
    its per-process samples with an extra ``stale="1"`` label instead of
    presenting dead numbers as fresh (the merged samples still include
    them — last-known-value semantics are deliberate for postmortems,
    the label just says so).  ``ingest_payload`` additionally retains the
    shipped flight-recorder ring and profiler stacks per source for the
    ``/flight`` endpoint and the merged flame view (profiler.py)."""

    STALE_FACTOR = 3.0

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # source -> {"snapshot": dict, "t": float monotonic,
        #            "flight": list|None, "profile": dict|None}
        self._sources: "OrderedDict[str, dict]" = OrderedDict()

    # ------------------------------------------------------------- ingest
    def ingest(self, source: str, snapshot: dict) -> None:
        if not isinstance(snapshot, dict):
            return
        with self._lock:
            entry = self._sources.get(str(source))
            if entry is None:
                entry = self._sources[str(source)] = {
                    "flight": None, "profile": None}
            entry["snapshot"] = snapshot
            entry["t"] = time.monotonic()

    def ingest_payload(self, source: str, payload: dict) -> None:
        """Ingest a full :func:`snapshot_payload` — the registry snapshot
        plus the side-band flight ring and profiler stacks.  A payload
        without a snapshot still refreshes the source's staleness clock
        (the process is alive and shipping)."""
        if not isinstance(payload, dict):
            return
        snap = payload.get("snapshot")
        with self._lock:
            entry = self._sources.get(str(source))
            if entry is None:
                entry = self._sources[str(source)] = {
                    "snapshot": {}, "flight": None, "profile": None}
            if isinstance(snap, dict) and snap:
                entry["snapshot"] = snap
            entry["t"] = time.monotonic()
            if isinstance(payload.get("flight"), list):
                entry["flight"] = payload["flight"]
            if isinstance(payload.get("profile"), dict):
                entry["profile"] = payload["profile"]

    def forget(self, source: str) -> None:
        with self._lock:
            self._sources.pop(str(source), None)

    def clear(self) -> None:
        with self._lock:
            self._sources.clear()

    def sources(self) -> List[str]:
        with self._lock:
            return list(self._sources)

    def profiles(self) -> Dict[str, dict]:
        """{source: latest shipped profiler snapshot} (profiler.py merges
        these into the driver-side flame view)."""
        with self._lock:
            return {s: e["profile"] for s, e in self._sources.items()
                    if e.get("profile")}

    def flight_rings(self) -> Dict[str, list]:
        """{source: latest shipped flight-recorder ring} — served by the
        ``/flight`` endpoint."""
        with self._lock:
            return {s: e["flight"] for s, e in self._sources.items()
                    if e.get("flight")}

    def staleness(self) -> Dict[str, float]:
        """{source: seconds since last ingest} (monotonic)."""
        now = time.monotonic()
        with self._lock:
            return {s: max(0.0, now - e.get("t", now))
                    for s, e in self._sources.items()}

    def _stale_cutoff(self) -> float:
        return self.STALE_FACTOR * ship_interval()

    def _snapshot_items(self, include_local: bool,
                        local_source: str) -> List[Tuple[str, dict]]:
        items: List[Tuple[str, dict]] = []
        if include_local:
            items.append((local_source, _local_snapshot()))
        with self._lock:
            items.extend((s, e["snapshot"])
                         for s, e in self._sources.items()
                         if "snapshot" in e
                         and (s != local_source or not include_local))
        return items

    def _stale_sources(self) -> set:
        cutoff = self._stale_cutoff()
        now = time.monotonic()
        with self._lock:
            return {s for s, e in self._sources.items()
                    if now - e.get("t", now) > cutoff}

    # ------------------------------------------------------------- totals
    def merged_totals(self, name: str, include_local: bool = True,
                      local_source: str = "driver",
                      ) -> Dict[Tuple[str, ...], float]:
        """{label values: summed value} for a scalar family across every
        source (histograms: summed ``sum``) — the programmatic read side
        tests and smokes assert against."""
        out: Dict[Tuple[str, ...], float] = {}
        for _source, snap in self._snapshot_items(include_local,
                                                  local_source):
            for fam in snap.get("families", ()):
                if fam.get("name") != name:
                    continue
                for child in fam.get("children", ()):
                    values = tuple(str(v) for v in child[0])
                    v = (float(child[2]) if fam.get("kind") == "histogram"
                         else float(child[1]))
                    out[values] = out.get(values, 0.0) + v
        return out

    # ------------------------------------------------------------- render
    def render_prometheus(self, include_local: bool = True,
                          local_source: str = "driver") -> str:
        from .catalog import help_for

        stale = self._stale_sources()
        fams: "OrderedDict[str, dict]" = OrderedDict()
        for source, snap in self._snapshot_items(include_local,
                                                 local_source):
            for f in snap.get("families", ()):
                name = f.get("name")
                if not name:
                    continue
                labels = tuple(f.get("labels", ()))
                entry = fams.get(name)
                if entry is None:
                    entry = fams[name] = {
                        "kind": f.get("kind", "untyped"),
                        "labels": labels,
                        "buckets": tuple(f.get("buckets", ())),
                        "help": f.get("help", ""),
                        "rows": [],
                    }
                elif (entry["kind"] != f.get("kind")
                      or entry["labels"] != labels):
                    continue  # conflicting signature: first source wins
                if not entry["help"] and f.get("help"):
                    entry["help"] = f["help"]
                entry["rows"].append((source, f))

        lines: List[str] = []
        for name, e in fams.items():
            help_text = e["help"] or help_for(name)
            if help_text:
                lines.append(f"# HELP {name} {_escape_help(help_text)}")
            lines.append(f"# TYPE {name} {e['kind']}")
            if e["kind"] == "histogram":
                self._render_hist(lines, name, e, stale)
            else:
                self._render_scalar(lines, name, e, stale)
        return "\n".join(lines) + "\n"

    @staticmethod
    def _proc_pairs(source: str, stale: set) -> List[Tuple[str, str]]:
        pairs = [(PROC_LABEL, source)]
        if source in stale:
            pairs.append(("stale", "1"))
        return pairs

    @staticmethod
    def _render_scalar(lines: List[str], name: str, e: dict,
                       stale: set) -> None:
        merged: "OrderedDict[Tuple[str, ...], float]" = OrderedDict()
        for source, f in e["rows"]:
            for child in sorted(f.get("children", ())):
                values = tuple(str(v) for v in child[0])
                val = float(child[1])
                pairs = MergedRegistry._proc_pairs(source, stale) + list(
                    zip(e["labels"], values))
                lines.append(f"{name}{_label_str(pairs)} {_fmt(val)}")
                merged[values] = merged.get(values, 0.0) + val
        for values, val in merged.items():
            pairs = list(zip(e["labels"], values))
            lines.append(f"{name}{_label_str(pairs)} {_fmt(val)}")

    @staticmethod
    def _render_hist(lines: List[str], name: str, e: dict,
                     stale: set) -> None:
        bounds = e["buckets"]
        # merged accumulation only over sources whose bounds match the
        # first-seen family (mismatched bounds still render per-process)
        merged: "OrderedDict[Tuple[str, ...], list]" = OrderedDict()
        for source, f in e["rows"]:
            f_bounds = tuple(f.get("buckets", ()))
            mergeable = f_bounds == bounds
            for child in sorted(f.get("children", ())):
                values = tuple(str(v) for v in child[0])
                counts = [int(c) for c in child[1]]
                # counts is authoritative: every _count line (per-source
                # and merged) renders from the cumulative bucket total,
                # so the shipped count field (child[3]) is not re-used
                s = float(child[2])
                if len(counts) != len(f_bounds) + 1:
                    continue  # malformed shipment
                # optional 5th element: exemplars as [bucket_i, value,
                # trace] triples (registry.py snapshot)
                ex: Dict[int, Tuple[float, str]] = {}
                if len(child) > 4 and isinstance(child[4], list):
                    for row in child[4]:
                        try:
                            ex[int(row[0])] = (float(row[1]), str(row[2]))
                        except (TypeError, ValueError, IndexError):
                            continue
                base = MergedRegistry._proc_pairs(source, stale) + list(
                    zip(e["labels"], values))
                cum = 0
                for i, (b, c) in enumerate(zip(f_bounds, counts)):
                    cum += c
                    pairs = base + [("le", _fmt(b))]
                    lines.append(f"{name}_bucket{_label_str(pairs)} {cum}"
                                 f"{_exemplar_str(ex.get(i))}")
                cum += counts[-1]
                lines.append(
                    f"{name}_bucket{_label_str(base + [('le', '+Inf')])} "
                    f"{cum}{_exemplar_str(ex.get(len(counts) - 1))}")
                lines.append(f"{name}_sum{_label_str(base)} {_fmt(s)}")
                lines.append(f"{name}_count{_label_str(base)} {cum}")
                if mergeable:
                    acc = merged.get(values)
                    if acc is None:
                        acc = merged[values] = [[0] * len(counts), 0.0, {}]
                    for i, c in enumerate(counts):
                        acc[0][i] += c
                    acc[1] += s
                    for i, pair in ex.items():
                        # merged exemplar per bucket: the max-latency
                        # observation across sources — "what was the p99"
                        cur = acc[2].get(i)
                        if cur is None or pair[0] >= cur[0]:
                            acc[2][i] = pair
        for values, (counts, s, ex) in merged.items():
            base = list(zip(e["labels"], values))
            cum = 0
            for i, (b, c) in enumerate(zip(bounds, counts)):
                cum += c
                pairs = base + [("le", _fmt(b))]
                lines.append(f"{name}_bucket{_label_str(pairs)} {cum}"
                             f"{_exemplar_str(ex.get(i))}")
            cum += counts[-1]
            lines.append(
                f"{name}_bucket{_label_str(base + [('le', '+Inf')])} {cum}"
                f"{_exemplar_str(ex.get(len(counts) - 1))}")
            lines.append(f"{name}_sum{_label_str(base)} {_fmt(s)}")
            lines.append(f"{name}_count{_label_str(base)} {cum}")


_merged = MergedRegistry()


def get_merged() -> MergedRegistry:
    """The process-default merged view (what the tracker and the fleet
    dispatcher ingest into, and what the scrape endpoint serves)."""
    return _merged


# ---------------------------------------------------------------------------
# Scrape endpoint
# ---------------------------------------------------------------------------


class _MetricsHandler(BaseHTTPRequestHandler):
    server_version = "xtb-metrics/1"

    def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        route = self.path.split("?", 1)[0].rstrip("/")
        if route in ("", "/metrics"):
            ctype = "text/plain; version=0.0.4; charset=utf-8"
            renderer = self.server.render  # type: ignore[attr-defined]
        elif route == "/healthz":
            ctype = "application/json"
            renderer = self.server.render_healthz  # type: ignore
        elif route == "/flight":
            ctype = "application/json"
            renderer = self.server.render_flight  # type: ignore
        else:
            self.send_error(404)
            return
        try:
            body = renderer().encode("utf-8")
        except Exception as e:  # pragma: no cover - render must not 500
            self.send_error(500, str(e))
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args) -> None:  # scrapes must not spam stderr
        pass


class MetricsServer(ThreadingHTTPServer):
    """``/metrics`` over the merged view (plus the local registry as
    source ``driver``).  ``port=0`` binds an ephemeral port; read the
    bound one from :attr:`port`.  Binds loopback by default — the
    endpoint is unauthenticated and leaks model/tenant names, so
    exposing it beyond the host is an explicit decision
    (``XGBOOST_TPU_METRICS_HOST=0.0.0.0`` or ``host=``)."""

    daemon_threads = True

    def __init__(self, port: int,
                 merged: Optional[MergedRegistry] = None,
                 include_local: bool = True,
                 host: Optional[str] = None) -> None:
        if host is None:
            host = os.environ.get(ENV_HOST, "").strip() or "127.0.0.1"
        super().__init__((host, int(port)), _MetricsHandler)
        self._merged = merged
        self._include_local = include_local
        self._thread: Optional[threading.Thread] = None

    def render(self) -> str:
        m = self._merged if self._merged is not None else get_merged()
        return m.render_prometheus(include_local=self._include_local)

    def _m(self) -> MergedRegistry:
        return self._merged if self._merged is not None else get_merged()

    def render_healthz(self) -> str:
        """Liveness + per-source staleness: {"status", "pid", "stale_after_s",
        "sources": {name: {"age_s", "stale"}}}.  200 as long as the server
        answers — the staleness map is the caller's signal, not the code."""
        import json

        m = self._m()
        cutoff = m._stale_cutoff()
        sources = {s: {"age_s": round(age, 3), "stale": age > cutoff}
                   for s, age in m.staleness().items()}
        return json.dumps({"status": "ok", "pid": os.getpid(),
                           "stale_after_s": round(cutoff, 3),
                           "sources": sources}, sort_keys=True)

    def render_flight(self) -> str:
        """Most recent flight-recorder rings as JSON: every shipped
        source's ring plus (when local is included) this process's own
        under "driver"."""
        import json

        rings = dict(self._m().flight_rings())
        if self._include_local:
            rings.setdefault("driver", flight.events())
        return json.dumps(rings, sort_keys=True, default=str)

    @property
    def port(self) -> int:
        return self.server_address[1]

    def start(self) -> "MetricsServer":
        if self._thread is None:
            self._thread = threading.Thread(target=self.serve_forever,
                                            daemon=True,
                                            name="xtb-metrics-http")
            self._thread.start()
        return self

    def close(self) -> None:
        self.shutdown()
        self.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


_server: Optional[MetricsServer] = None
_server_lock = threading.Lock()


def start_metrics_server(port: Optional[int] = None,
                         ) -> Optional[MetricsServer]:
    """Start (or return) the process-wide scrape endpoint.  With
    ``port=None`` the port comes from ``XGBOOST_TPU_METRICS_PORT``; the
    variable absent or <= 0 means disabled (returns None).  An explicit
    ``port`` argument always starts one (0 = ephemeral)."""
    global _server
    with _server_lock:
        if _server is not None:
            return _server
        if port is None:
            raw = os.environ.get(ENV_PORT, "").strip()
            if not raw:
                return None
            try:
                port = int(raw)
            except ValueError:
                return None
            if port <= 0:
                return None
        try:
            _server = MetricsServer(port).start()
        except OSError as e:
            # an opt-in observability endpoint failing to bind (port in
            # use, restart race) must never take training/serving down
            import warnings

            warnings.warn(f"metrics endpoint on port {port} not started "
                          f"({e}); continuing without a scrape endpoint",
                          RuntimeWarning, stacklevel=2)
            return None
        return _server


def stop_metrics_server() -> None:
    global _server
    with _server_lock:
        srv, _server = _server, None
    if srv is not None:
        srv.close()


# ---------------------------------------------------------------------------
# Worker-side shipping (tracker channel)
# ---------------------------------------------------------------------------

_last_ship = 0.0


def ship_to_tracker(force: bool = False) -> bool:
    """Ship this process's snapshot to the rendezvous tracker over the
    persistent channel (tracker-mode training workers only; other
    backends return False).  Rate-limited to :func:`ship_interval`
    unless ``force`` — ``TelemetryCallback`` calls this every round and
    ``collective.finalize`` forces a final ship at exit."""
    global _last_ship
    from .. import collective

    backend = collective._backend()
    tracker = getattr(backend, "_tracker", None)
    if tracker is None or not hasattr(tracker, "ship_telemetry"):
        return False
    now = time.monotonic()
    if not force and now - _last_ship < ship_interval():
        return False
    _last_ship = now
    return bool(tracker.ship_telemetry(snapshot_payload()))
