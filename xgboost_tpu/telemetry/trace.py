"""JSONL event-trace writer (chrome://tracing / Perfetto compatible).

Each record is one JSON object per line with the Trace Event Format's
complete-event fields — ``{"name", "ph": "X", "ts", "dur", "pid", "tid"}``
(timestamps/durations in microseconds) — so a capture loads directly in
chrome://tracing or ui.perfetto.dev after wrapping the lines in a JSON
array (scripts in docs/observability.md), and line-oriented tools (jq,
grep) can stream it without parsing the whole file.

Gated by ``XGBOOST_TPU_TRACE=<path>``: when set at import (or via
``configure(path)``) every span (spans.py), Monitor bracket
(utils/timer.py), serving batch, and XLA compile appends one line.  The
writer is append-only behind a small lock, opens the file lazily on the
first event, and flushes per line so a crashed run still leaves a valid
parseable prefix.
"""
from __future__ import annotations

import atexit
import io
import json
import os
import threading
from typing import Optional

__all__ = ["active", "configure", "emit", "emit_meta", "path", "flush",
           "set_process_name", "set_thread_name", "ENV_VAR"]

ENV_VAR = "XGBOOST_TPU_TRACE"
_OWNER_VAR = ENV_VAR + "_OWNER_PID"


def _env_path() -> Optional[str]:
    """Resolve the env-configured destination.  Multi-process training
    (launcher.py) spawns workers that inherit XGBOOST_TPU_TRACE; every
    process truncating and buffering into ONE file would interleave
    garbage, so the first process to import claims the bare path (owner
    marker env var, inherited by children) and every other process writes
    ``<path>.<pid>`` — one valid JSONL per process, pid field in every
    event for merging."""
    path = os.environ.get(ENV_VAR) or None
    if path is None:
        return None
    owner = os.environ.get(_OWNER_VAR)
    me = str(os.getpid())
    if owner is None:
        os.environ[_OWNER_VAR] = me
    elif owner != me:
        path = f"{path}.{me}"
    return path


_lock = threading.Lock()
_path: Optional[str] = _env_path()
_file: Optional[io.TextIOBase] = None
_configured_export = False  # True only when configure(path) set ENV_VAR
_env_before_export: Optional[str] = None  # user's value, restored on None


def active() -> bool:
    """True when a trace destination is configured."""
    return _path is not None


def path() -> Optional[str]:
    return _path


def configure(path: Optional[str]) -> None:
    """Set (or with None, stop) the JSONL destination programmatically —
    the same switch as the XGBOOST_TPU_TRACE environment variable,
    including auto-enabling the span tracer (a trace with no spans is
    never what the caller wanted).  configure(None) stops writing but
    leaves the span flag alone — it may have been enabled explicitly.

    Like the env-var path, configure(path) claims ownership: the variable
    (and the owner-pid marker) are exported so subprocesses spawned after
    this call — fleet replicas, launcher workers — capture their own
    ``<path>.<pid>`` sidecar files instead of truncating ours; the merged
    multi-process timeline is their union (docs/observability.md).
    configure(None) undoes only an export configure(path) ITSELF made —
    a variable the user set in the launching environment is restored,
    never deleted."""
    global _path, _file, _configured_export, _env_before_export
    with _lock:
        if _file is not None:
            try:
                _file.flush()
                _file.close()
            except OSError:  # pragma: no cover - fs teardown race
                pass
            _file = None
        _path = path or None
    if _path is not None:
        if not _configured_export:
            _env_before_export = os.environ.get(ENV_VAR)
            _configured_export = True
        os.environ[ENV_VAR] = _path
        os.environ.setdefault(_OWNER_VAR, str(os.getpid()))
        from . import spans  # import cycle broken at call time

        spans.enable()
    elif _configured_export:
        _configured_export = False
        if _env_before_export is not None:
            os.environ[ENV_VAR] = _env_before_export
        else:
            os.environ.pop(ENV_VAR, None)
            if os.environ.get(_OWNER_VAR) == str(os.getpid()):
                os.environ.pop(_OWNER_VAR, None)
        _env_before_export = None


def _ensure_file() -> Optional[io.TextIOBase]:
    global _file
    if _file is None and _path is not None:
        # truncate: one capture = one process run (perf_counter timestamps
        # have a per-process epoch, so appending across runs would render
        # as one garbage timeline in chrome://tracing); the file stays open
        # for appends within this run
        _file = open(_path, "w", encoding="utf-8")
    return _file


def emit(name: str, ts_ns: int, dur_ns: int, ph: str = "X",
         **args) -> None:
    """Append one complete event.  ``ts_ns`` is the perf_counter_ns start of
    the span; chrome expects microseconds, so both fields divide by 1e3."""
    if _path is None:
        return
    rec = {
        "name": name,
        "ph": ph,
        "ts": ts_ns / 1e3,
        "dur": dur_ns / 1e3,
        "pid": os.getpid(),
        "tid": threading.get_ident() & 0x7FFFFFFF,
    }
    if args:
        rec["args"] = args
    line = json.dumps(rec, separators=(",", ":"))
    with _lock:
        f = _ensure_file()
        if f is None:  # configure(None) raced us
            return
        f.write(line + "\n")
        f.flush()


def emit_meta(meta: str, value: str) -> None:
    """Append one Trace Event Format metadata record (``ph="M"``) —
    ``process_name`` / ``thread_name`` entries that make a merged
    multi-process capture readable (the viewer shows ``replica0`` or
    ``rank2`` instead of bare pids).  ``dur``/``ts`` ride along as zeros
    so line-oriented consumers see the same field set as span events."""
    if _path is None:
        return
    rec = {
        "name": meta,
        "ph": "M",
        "ts": 0.0,
        "dur": 0.0,
        "pid": os.getpid(),
        "tid": threading.get_ident() & 0x7FFFFFFF,
        "args": {"name": value},
    }
    line = json.dumps(rec, separators=(",", ":"))
    with _lock:
        f = _ensure_file()
        if f is None:
            return
        f.write(line + "\n")
        f.flush()


def set_process_name(label: str) -> None:
    """Name this process in the merged timeline (``replica0``,
    ``rank3``, ``fleet-driver``...).  No-op when tracing is off."""
    emit_meta("process_name", label)


def set_thread_name(name: str) -> None:
    emit_meta("thread_name", name)


def flush() -> None:
    with _lock:
        if _file is not None:
            _file.flush()


@atexit.register
def _close() -> None:  # pragma: no cover - interpreter teardown
    global _file, _path
    # bounded acquire (XTB903): an emitter thread wedged on the lock must
    # not hang interpreter shutdown — losing the last flush beats never
    # exiting
    if not _lock.acquire(timeout=1.0):
        return
    try:
        if _file is not None:
            try:
                _file.flush()
                _file.close()
            except OSError:
                pass
            # later LIFO atexit hooks may still emit(): with _path cleared
            # they no-op instead of writing to a closed handle
            _file = None
            _path = None
    finally:
        _lock.release()
