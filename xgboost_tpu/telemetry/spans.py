"""Span tracer: named wall-clock brackets over the training/serving hot path.

``span("grow.build_hist")`` is a context manager that, when telemetry is
enabled, records ``time.perf_counter_ns`` duration into the registry
histogram ``xtb_phase_seconds{phase=...}``, appends a JSONL trace event
(trace.py) when ``XGBOOST_TPU_TRACE`` is set, and opens a
``jax.profiler.TraceAnnotation`` so the same label shows up in TPU/perfetto
profiler captures — one bracket, three sinks.

Disabled-by-default overhead is the design constraint (the hot path calls
``span()`` per tree level): everything hangs off ONE module-level flag, and
the disabled path is a flag test plus returning a shared no-op context
manager — no allocation, no clock read, no dict lookup
(tests/test_telemetry.py has the guard test).

``utils/timer.Monitor`` is a thin shim over ``record_phase`` (same sinks,
stack-based start/stop bracketing); use ``span`` directly in new code.
"""
from __future__ import annotations

import os
import time
from typing import Dict, Optional

from . import flight, trace
from .registry import get_registry

__all__ = ["span", "enable", "disable", "enabled", "record_phase", "Span",
           "phase_totals", "PHASE_HISTOGRAM"]

PHASE_HISTOGRAM = "xtb_phase_seconds"

# the ONE flag every span checks; a configured trace destination implies
# spans are wanted (capturing an empty trace would be the only alternative)
_ENABLED: bool = bool(os.environ.get(trace.ENV_VAR))

_phase_hist = None  # created lazily so importing telemetry stays cheap
_children: Dict[str, object] = {}  # phase name -> histogram child (cached)
_profiler = 0  # 0 = unprobed, module when available, None when not


def enabled() -> bool:
    return _ENABLED


def enable(on: bool = True) -> None:
    """Turn span bookkeeping on (idempotent; process-wide)."""
    global _ENABLED
    _ENABLED = bool(on)


def disable() -> None:
    enable(False)


def _hist():
    global _phase_hist
    if _phase_hist is None:
        _phase_hist = get_registry().histogram(
            PHASE_HISTOGRAM,
            "wall-clock seconds per instrumented phase", ("phase",))
    return _phase_hist


def _child(name: str):
    child = _children.get(name)
    if child is None:
        child = _children.setdefault(name, _hist().labels(name))
    return child


def _annotation(name: str):
    """jax.profiler.TraceAnnotation(name).__enter__() or None — guarded so
    telemetry works before/without jax initialization."""
    global _profiler
    if _profiler == 0:
        try:
            import jax.profiler as _p
            _profiler = _p
        except Exception:  # pragma: no cover - no jax in the process
            _profiler = None
    if _profiler is None:  # pragma: no cover - no jax in the process
        return None
    try:
        ann = _profiler.TraceAnnotation(name)
        ann.__enter__()
        return ann
    except Exception:  # pragma: no cover - profiler backend quirk
        return None


def record_phase(name: str, t0_ns: int, dur_ns: int) -> None:
    """Feed one finished bracket into the sinks (histogram + flight ring
    + JSONL trace).  Shared by Span and the Monitor shim so they agree on
    format.  The flight append keeps the crash ring carrying the last few
    hundred spans even when no trace file is configured."""
    _child(name).observe(dur_ns / 1e9)
    flight.record("span", name, s=dur_ns / 1e9)
    if trace.active():
        trace.emit(name, t0_ns, dur_ns)


class Span:
    """One enabled bracket.  Usable as a context manager or via explicit
    begin()/end() (the Monitor shim drives it manually)."""

    __slots__ = ("name", "t0", "_ann")

    def __init__(self, name: str) -> None:
        self.name = name
        self.t0 = 0
        self._ann = None

    def begin(self) -> "Span":
        self._ann = _annotation(self.name)
        self.t0 = time.perf_counter_ns()
        return self

    def end(self) -> int:
        dur = time.perf_counter_ns() - self.t0
        ann = self._ann
        if ann is not None:
            self._ann = None
            try:
                ann.__exit__(None, None, None)
            except Exception:  # pragma: no cover - profiler backend quirk
                pass
        record_phase(self.name, self.t0, dur)
        return dur

    def __enter__(self) -> "Span":
        return self.begin()

    def __exit__(self, *exc) -> None:
        self.end()


class _NullSpan:
    """Shared no-op: the disabled path allocates nothing."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass

    def begin(self) -> "_NullSpan":
        return self

    def end(self) -> int:
        return 0


_NULL = _NullSpan()


def span(name: str):
    """The instrumentation entry point: a live Span when telemetry is
    enabled, the shared no-op otherwise."""
    return Span(name) if _ENABLED else _NULL


def phase_totals() -> Dict[str, Dict[str, float]]:
    """{phase: {"count": n, "seconds": s}} accumulated so far — the
    inspectable read side (render_prometheus() has the full histogram)."""
    hist = get_registry().get(PHASE_HISTOGRAM)
    if hist is None:
        return {}
    return {values[0]: {"count": c, "seconds": s}
            for values, (c, s) in hist.snapshot_sums().items()}
