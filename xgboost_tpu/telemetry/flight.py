"""Flight recorder: a fixed-size in-process ring of recent spans, events,
and faults — the postmortem channel that works WITHOUT tracing enabled.

The JSONL trace answers "where did the time go" but costs a file per
process and must be switched on before the run; the flight recorder
answers "what was this process doing right before it died" and is always
armed: a bounded ``collections.deque`` of small dicts that coarse seams
append to unconditionally (round boundaries, control ops, injected
faults, replica/worker deaths) and that enabled spans also feed, so a
crash dump shows the last few hundred things the process did.

Three exits for the ring:

- **dump(path)** — one-shot JSON file (atomic tmp+rename).  ``install()``
  registers it atexit and the launcher/replica crash paths call it
  explicitly, so an exception death leaves a dump.
- **periodic spill** — ``install()`` arms a cheap time-gated spill inside
  :func:`record` (default every ``XGBOOST_TPU_FLIGHT_SPILL_S`` = 5s), so
  even a SIGKILL'd process leaves a recent-past dump on disk.
- **shipping** — fleet replicas and tracker-mode training workers ship
  ``events()`` alongside their registry snapshots
  (telemetry/distributed.py); the driver retains the last ring per
  process and dumps it when the process dies, which is how a SIGKILL'd
  replica's final moments survive driver-side.

Timestamps are ``time.monotonic()`` (the repo's nondeterminism lint bans
wall-clock reads in library code); every dump carries a wall-clock anchor
pair (``wall_at_dump`` ISO-8601 + ``mono_at_dump``) so consumers can
reconstruct absolute times.

Dump location: ``XGBOOST_TPU_FLIGHT_DIR`` (default
``<tmp>/xtb_flight``), file ``flight_<label>.json`` where the label comes
from :func:`install`/``XGBOOST_TPU_FLIGHT_LABEL`` (the launcher sets it
per worker) and falls back to ``pid<pid>``.
"""
from __future__ import annotations

import atexit
import json
import os
import tempfile
import threading
import time
from collections import deque
from datetime import datetime, timezone
from typing import Any, Dict, List, Optional

__all__ = ["record", "events", "dump", "dump_stacks", "install",
           "dump_dir", "default_path", "stacks_path", "set_label", "clear",
           "ENV_DIR", "ENV_LABEL", "ENV_SIZE", "ENV_SPILL"]

ENV_DIR = "XGBOOST_TPU_FLIGHT_DIR"
ENV_LABEL = "XGBOOST_TPU_FLIGHT_LABEL"
ENV_SIZE = "XGBOOST_TPU_FLIGHT_SIZE"
ENV_SPILL = "XGBOOST_TPU_FLIGHT_SPILL_S"


def _ring_size() -> int:
    try:
        return max(16, int(os.environ.get(ENV_SIZE, "512")))
    except ValueError:
        return 512


_lock = threading.Lock()
_ring: "deque[Dict[str, Any]]" = deque(maxlen=_ring_size())
_label: Optional[str] = os.environ.get(ENV_LABEL) or None
_spill_path: Optional[str] = None
_spill_interval: float = 5.0
_last_spill: float = 0.0
_installed = False


def dump_dir() -> str:
    d = os.environ.get(ENV_DIR) or os.path.join(tempfile.gettempdir(),
                                                "xtb_flight")
    os.makedirs(d, exist_ok=True)
    return d


def _resolved_label() -> str:
    return _label or os.environ.get(ENV_LABEL) or f"pid{os.getpid()}"


def default_path(label: Optional[str] = None) -> str:
    return os.path.join(dump_dir(),
                        f"flight_{label or _resolved_label()}.json")


def set_label(label: str) -> None:
    global _label
    _label = str(label)


def record(kind: str, name: str, **detail: Any) -> None:
    """Append one event to the ring; never raises (observability must not
    take the process down).  ``kind`` is one of ``span``/``event``/
    ``fault`` by convention; ``detail`` must be JSON-serializable."""
    try:
        rec: Dict[str, Any] = {"t_mono": time.monotonic(), "kind": kind,
                               "name": name}
        if detail:
            rec["detail"] = detail
        with _lock:
            _ring.append(rec)
        if _spill_path is not None:
            _maybe_spill()
    except Exception:  # pragma: no cover - defensive
        pass


def events() -> List[Dict[str, Any]]:
    with _lock:
        return list(_ring)


def _payload(evs: List[Dict[str, Any]]) -> Dict[str, Any]:
    return {
        "label": _resolved_label(),
        "pid": os.getpid(),
        "wall_at_dump": datetime.now(timezone.utc).isoformat(),
        "mono_at_dump": time.monotonic(),
        "events": evs,
    }


def _write(path: str, evs: List[Dict[str, Any]]) -> str:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(_payload(evs), fh)
    os.replace(tmp, path)
    return path


def dump(path: Optional[str] = None) -> str:
    """Write the ring to ``path`` (default :func:`default_path`)
    atomically; returns the path.  Safe to call repeatedly — each call
    replaces the file with the current ring."""
    return _write(path or default_path(), events())


def stacks_path(label: Optional[str] = None) -> str:
    """Where :func:`dump_stacks` writes for ``label`` (same directory and
    labeling scheme as the ring dump, so a postmortem finds both)."""
    return os.path.join(dump_dir(),
                        f"stacks_{label or _resolved_label()}.txt")


def dump_stacks(path: Optional[str] = None) -> Optional[str]:
    """``faulthandler.dump_traceback`` of ALL threads into the flight
    directory (append — successive dumps of one process stay in order,
    separated by a monotonic-stamped header line).  The crash/abort path
    of every spawned process and the watchdog's dump stage both land
    here, so "what was every thread doing" survives without a debugger
    attached.  Returns the path, or None when the write failed — stack
    dumping must never take the dying process down faster."""
    import faulthandler

    path = path or stacks_path()
    try:
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(f"=== stacks pid={os.getpid()} "
                     f"label={_resolved_label()} "
                     f"mono={time.monotonic():.3f} ===\n")
            fh.flush()
            faulthandler.dump_traceback(file=fh, all_threads=True)
            fh.write("\n")
        record("event", "flight.stacks", path=path)
        return path
    except Exception:  # pragma: no cover - fs trouble on the death path
        return None


def _maybe_spill() -> None:
    global _last_spill
    now = time.monotonic()
    if now - _last_spill < _spill_interval:
        return
    _last_spill = now
    try:
        dump(_spill_path)
    except OSError:  # pragma: no cover - fs trouble must not kill the app
        pass


def install(label: Optional[str] = None,
            spill_interval_s: Optional[float] = None) -> str:
    """Arm this process's recorder: set the dump label, enable the
    periodic spill, and register an atexit dump.  Returns the dump path.
    Idempotent (the launcher child stub and the replica both call it)."""
    global _spill_path, _spill_interval, _installed
    if label:
        set_label(label)
    if spill_interval_s is None:
        try:
            spill_interval_s = float(os.environ.get(ENV_SPILL, "5.0"))
        except ValueError:
            spill_interval_s = 5.0
    path = default_path()
    with _lock:
        _spill_path = path
        _spill_interval = max(0.1, float(spill_interval_s))
        first = not _installed
        _installed = True
    if first:
        atexit.register(_atexit_dump)
    return path


def _atexit_dump() -> None:  # pragma: no cover - interpreter teardown
    # bounded acquire (XTB903): a recorder wedged on the ring lock must
    # not hang shutdown; an unlocked best-effort snapshot beats no dump
    # at all on the death path
    try:
        if _lock.acquire(timeout=1.0):
            try:
                evs = list(_ring)
            finally:
                _lock.release()
        else:
            evs = list(_ring)
        _write(_spill_path or default_path(), evs)
    except Exception:
        pass


def clear() -> None:
    """Drop every buffered event (test isolation)."""
    with _lock:
        _ring.clear()
