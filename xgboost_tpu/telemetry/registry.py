"""Lock-cheap metrics registry: Counter / Gauge / Histogram with labels.

Role model: the Prometheus client data model (counter/gauge/histogram
families keyed by label sets), stripped to what a training/serving process
needs.  The reference exposes none of this — its c_api returns raw buffers
and timing lives in stderr prints at verbosity 3 — so the registry is the
repo's single source of SLO signals: ``serving/metrics.ServingMetrics`` is
rebased onto it, the span tracer (spans.py) records phase durations into
it, and compile accounting (compile.py) counts retraces into it.

Lock discipline: one ``threading.Lock`` per metric family, held for a dict
lookup plus a few float adds — O(1) and contention-free in practice (the
serving hot path takes it once per request).  Label children are cached on
first use so steady-state increments never allocate.

``render_prometheus()`` emits the text exposition format (``# HELP`` /
``# TYPE`` + one line per sample) ready for a scrape endpoint; see
docs/observability.md for the serving example.
"""
from __future__ import annotations

import bisect
import math
import threading
import time
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "get_registry",
    "render_prometheus", "DEFAULT_BUCKETS",
]
# _escape_label/_escape_help/_fmt are shared with telemetry/distributed.py
# so merged and local exposition agree byte-for-byte on formatting.

# seconds-scale exponential buckets: 100us .. ~100s (phase timings and
# request latencies both land comfortably inside)
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(
    1e-4 * (4.0 ** i) for i in range(10)
)


def _validate_name(name: str) -> None:
    # Prometheus exposition: [a-zA-Z_:][a-zA-Z0-9_:]*
    if (not name or not name.isascii() or name[0].isdigit()
            or not all(c.isalnum() or c in "_:" for c in name)):
        raise ValueError(f"invalid metric name {name!r}")


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _escape_help(v: str) -> str:
    # exposition format: HELP text escapes backslash and newline (an
    # unescaped newline splits the comment into a garbage sample line)
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


class _Family:
    """Base metric family: a name + label names + cached label children."""

    kind = "untyped"

    def __init__(self, name: str, help: str, label_names: Sequence[str] = (),
                 ) -> None:
        _validate_name(name)
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            values = tuple(str(kv[n]) for n in self.label_names)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {values}")
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(values, self._child())
        return child

    def _child(self):  # pragma: no cover - abstract
        raise NotImplementedError

    # ------------------------------------------------------------- read side
    def collect(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return list(self._children.items())

    def _label_str(self, values: Tuple[str, ...], extra: str = "") -> str:
        parts = [f'{n}="{_escape_label(v)}"'
                 for n, v in zip(self.label_names, values)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""


class _Value:
    """A single float cell guarded by its family's lock."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock) -> None:
        self._lock = lock
        self.value = 0.0


class _CounterChild(_Value):
    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += amount

    def get(self) -> float:
        return self.value


class _ScalarFamily(_Family):
    """Shared single-sample-per-child rendering for Counter and Gauge."""

    def get(self, *values, **kv) -> float:
        return self.labels(*values, **kv).get()

    def render(self) -> Iterable[str]:
        for values, child in sorted(self.collect()):
            yield f"{self.name}{self._label_str(values)} {_fmt(child.value)}"


class Counter(_ScalarFamily):
    kind = "counter"

    def _child(self) -> _CounterChild:
        return _CounterChild(self._lock)

    def inc(self, amount: float = 1.0) -> None:
        """Increment the label-less child (families with labels must go
        through .labels())."""
        self.labels().inc(amount)


class _GaugeChild(_Value):
    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def set_max(self, v: float) -> None:
        """Atomic monotonic raise — the high-water-mark update (a separate
        get()/set() pair would let a stale writer regress the mark)."""
        with self._lock:
            if v > self.value:
                self.value = float(v)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount

    def get(self) -> float:
        return self.value


class Gauge(_ScalarFamily):
    kind = "gauge"

    def _child(self) -> _GaugeChild:
        return _GaugeChild(self._lock)

    def set(self, v: float) -> None:
        self.labels().set(v)

    def set_max(self, v: float) -> None:
        self.labels().set_max(v)

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)


EXEMPLAR_WINDOW_S = 60.0  # a bucket's max-latency exemplar ages out after
# this many (monotonic) seconds, so one startup outlier cannot pin the
# bucket's trace id forever


class _HistogramChild:
    __slots__ = ("_lock", "_bounds", "counts", "sum", "count", "exemplars")

    def __init__(self, lock: threading.Lock, bounds: Tuple[float, ...]):
        self._lock = lock
        self._bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0
        # per-bucket (value, trace_id, t_mono) of the max observation in
        # the current window, or None; allocated on first exemplar so
        # exemplar-free histograms pay nothing
        self.exemplars: Optional[list] = None

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        i = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1
            if exemplar is not None:
                ex = self.exemplars
                if ex is None:
                    ex = self.exemplars = [None] * len(self.counts)
                cur = ex[i]
                now = time.monotonic()
                if (cur is None or v >= cur[0]
                        or now - cur[2] > EXEMPLAR_WINDOW_S):
                    ex[i] = (float(v), str(exemplar), now)

    def exemplar_items(self) -> List[Tuple[int, float, str]]:
        """[(bucket_index, value, trace_id)] for buckets holding a live
        (non-aged-out) exemplar."""
        with self._lock:
            if not self.exemplars:
                return []
            now = time.monotonic()
            return [(i, e[0], e[1]) for i, e in enumerate(self.exemplars)
                    if e is not None and now - e[2] <= EXEMPLAR_WINDOW_S]

    def merge_bucketed(self, counts: Sequence[int], sum_: float,
                       count: int) -> None:
        """Fold observations that were already bucketed elsewhere (the
        native ParallelFor pool keeps per-kernel duration buckets in C++
        with these exact bounds; telemetry/native_pool.py bridges the
        deltas here).  ``counts`` must cover every bucket incl. overflow."""
        if len(counts) != len(self.counts):
            raise ValueError(
                f"expected {len(self.counts)} bucket counts, got "
                f"{len(counts)}")
        with self._lock:
            for i, c in enumerate(counts):
                self.counts[i] += int(c)
            self.sum += float(sum_)
            self.count += int(count)


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name: str, help: str, label_names: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        super().__init__(name, help, label_names)
        b = tuple(sorted(float(x) for x in buckets))
        if not b or not all(math.isfinite(x) for x in b):
            # an explicit +Inf bound would collide with the implicit
            # overflow bucket (duplicate le="+Inf" samples = invalid scrape)
            raise ValueError("histogram needs at least one finite bucket "
                             "and no non-finite bounds")
        self.buckets = b

    def _child(self) -> _HistogramChild:
        return _HistogramChild(self._lock, self.buckets)

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        self.labels().observe(v, exemplar)

    def snapshot_sums(self) -> Dict[Tuple[str, ...], Tuple[int, float]]:
        """{label values: (count, sum)} — the cheap read the per-round
        TelemetryCallback diffs to attribute phase time."""
        out = {}
        with self._lock:
            for values, child in self._children.items():
                out[values] = (child.count, child.sum)
        return out

    def render(self) -> Iterable[str]:
        for values, child in sorted(self.collect()):
            ex = {i: (v, t) for i, v, t in child.exemplar_items()}
            cum = 0
            for i, (bound, c) in enumerate(zip(self.buckets, child.counts)):
                cum += c
                le = self._label_str(values, f'le="{_fmt(bound)}"')
                yield (f"{self.name}_bucket{le} {cum}"
                       f"{_exemplar_str(ex.get(i))}")
            cum += child.counts[-1]
            le = self._label_str(values, 'le="+Inf"')
            yield (f"{self.name}_bucket{le} {cum}"
                   f"{_exemplar_str(ex.get(len(child.counts) - 1))}")
            yield (f"{self.name}_sum{self._label_str(values)} "
                   f"{_fmt(child.sum)}")
            yield f"{self.name}_count{self._label_str(values)} {cum}"


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _exemplar_str(pair: Optional[Tuple[float, str]]) -> str:
    """OpenMetrics exemplar suffix for a bucket sample line:
    `` # {trace="<id>"} <value>`` — the trace id of the window's
    max-latency observation, resolvable against the flight recorder /
    chrome trace (shared with distributed.py so local and merged
    exposition agree byte-for-byte)."""
    if pair is None:
        return ""
    v, trace = pair
    return f' # {{trace="{_escape_label(trace)}"}} {_fmt(v)}'


class Registry:
    """Named metric families; get-or-create is idempotent per (name, kind)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: "OrderedDict[str, _Family]" = OrderedDict()

    def _get_or_create(self, cls, name: str, help: str, label_names,
                       **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or fam.label_names != tuple(
                        label_names):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind} with labels {fam.label_names}")
                if "buckets" in kw and fam.buckets != tuple(
                        sorted(float(x) for x in kw["buckets"])):
                    # silently handing back different boundaries would put
                    # the caller's observations in the wrong buckets
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {fam.buckets}")
                return fam
            fam = cls(name, help, label_names, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                label_names: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, label_names)

    def gauge(self, name: str, help: str = "",
              label_names: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, label_names)

    def histogram(self, name: str, help: str = "",
                  label_names: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, label_names,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def families(self) -> List[_Family]:
        with self._lock:
            return list(self._families.values())

    def snapshot(self) -> dict:
        """JSON-serializable dump of every family — the cross-process
        shipping format (telemetry/distributed.py merges these driver-side
        into a :class:`~xgboost_tpu.telemetry.distributed.MergedRegistry`).

        Scalars ship ``[label_values, value]``; histograms ship
        ``[label_values, bucket_counts, sum, count]`` with the family's
        bucket bounds alongside, so the receiver can fold them via the
        same bucketed-merge path the native pool bridge uses.  A
        histogram child holding live latency exemplars appends a fifth
        element ``[[bucket_i, value, trace], ...]`` — receivers that
        predate exemplars ignore extra elements."""
        fams = []
        for fam in self.families():
            rec: dict = {"name": fam.name, "kind": fam.kind,
                         "help": fam.help,
                         "labels": list(fam.label_names)}
            if fam.kind == "histogram":
                rec["buckets"] = [float(b) for b in fam.buckets]
                children = []
                for values, child in fam.collect():
                    row = [list(values), [int(c) for c in child.counts],
                           float(child.sum), int(child.count)]
                    ex = child.exemplar_items()
                    if ex:
                        row.append([[i, v, t] for i, v, t in ex])
                    children.append(row)
                rec["children"] = children
            else:
                rec["children"] = [[list(values), float(child.value)]
                                   for values, child in fam.collect()]
            fams.append(rec)
        return {"families": fams}

    def render_prometheus(self) -> str:
        from .catalog import help_for  # lazy: parses the docs catalog once

        lines: List[str] = []
        for fam in self.families():
            help_text = fam.help or help_for(fam.name)
            if help_text:
                lines.append(f"# HELP {fam.name} {_escape_help(help_text)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            lines.extend(fam.render())
        return "\n".join(lines) + "\n"

_default = Registry()


def get_registry() -> Registry:
    return _default


def render_prometheus() -> str:
    """Text exposition of the process-default registry."""
    return _default.render_prometheus()
