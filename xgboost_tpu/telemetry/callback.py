"""TelemetryCallback: per-round phase timings, tree stats, and compile
accounting as an inspectable history.

A TrainingCallback (callback.py contract) that diffs the span histogram
(spans.py PHASE_HISTOGRAM) and the compile counter around every boosting
round, and reads the committed model for structural stats — so a training
run leaves a round-by-round record of where the time went and whether any
round retraced, without touching the training loop itself::

    cb = TelemetryCallback()
    xtb.train(params, d, 10, callbacks=[cb])
    cb.history[3]["phases"]["grow.update_tree"]   # seconds in round 3
    cb.history[3]["trees"][0]["leaves"]
    cb.compiles_steady                            # SLO: 0 after round 0

Round 0 is the warm-up round (every level program traces there); compiles
in later rounds are steady-state retraces and feed the registry counter
``xtb_compiles_steady{scope="train"}`` — the same no-retrace SLO gauge the
serving engine keeps (serving/metrics.py), scoped per subsystem.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ..callback import TrainingCallback
from . import compile as _compile
from . import spans
from .registry import get_registry

__all__ = ["TelemetryCallback"]


class TelemetryCallback(TrainingCallback):
    """Records per-round telemetry into ``self.history`` (list of dicts).

    Parameters
    ----------
    enable_spans : bool
        Turn the span tracer on in before_training (default True) so the
        phase attribution is populated even when the caller never called
        ``telemetry.enable()``.  The flag is left as-is on after_training
        (process-wide state; flipping it back could disable a concurrent
        consumer's spans).
    straggler : bool
        Distributed only: allgather every rank's round wall + collective
        wait at each round boundary and record a straggler report
        (``history[i]["straggler"]``: per-rank walls, max/min rank,
        spread).  This ADDS one collective per round, so it must be
        enabled on EVERY rank or the job wedges — and it is not for
        elastic runs (the extra gather shifts the relay seq numbering a
        regroup replays).  Default off.
    """

    def __init__(self, enable_spans: bool = True,
                 straggler: bool = False) -> None:
        self.enable_spans = enable_spans
        self.straggler = straggler
        self.history: List[Dict[str, Any]] = []
        self.compiles_warmup = 0
        self.compiles_steady = 0
        self._phase0: Dict[str, Dict[str, float]] = {}
        self._coll0: Dict[Any, Any] = {}
        self._compiles0 = 0
        self._t0 = 0.0
        self._ntrees0 = 0
        self._warm_round: Optional[int] = None  # first round of current run
        self._steady_counter = None

    # ------------------------------------------------- TrainingCallback API
    def before_training(self, model):
        if self.enable_spans and not spans.enabled():
            spans.enable()
        self._ntrees0 = len(getattr(model, "trees", ()))
        # new training run: its first round is warm-up again, even when the
        # callback is reused across train() calls (each run compiles its own
        # level programs; lifetime history must not reclassify them steady)
        self._warm_round = None
        return model

    def after_training(self, model):
        return model

    def before_iteration(self, model, epoch: int, evals_log) -> bool:
        self._phase0 = spans.phase_totals()
        self._coll0 = self._coll_sums()
        self._compiles0 = _compile.compiles_total()
        self._t0 = time.perf_counter()
        return False

    def after_iteration(self, model, epoch: int, evals_log) -> bool:
        seconds = time.perf_counter() - self._t0
        cur = spans.phase_totals()
        phases = {}
        for name, tot in cur.items():
            prev = self._phase0.get(name)
            ds = tot["seconds"] - (prev["seconds"] if prev else 0.0)
            dc = tot["count"] - (prev["count"] if prev else 0)
            if dc:
                phases[name] = {"seconds": ds, "count": int(dc)}
        compiles = _compile.compiles_total() - self._compiles0
        trees = self._tree_stats(model)
        rec: Dict[str, Any] = {
            "round": int(epoch),
            "seconds": seconds,
            "phases": phases,
            "compiles": int(compiles),
            "trees": trees,
        }
        coll = self._coll_delta(self._coll0)
        if coll["count"]:
            rec["coll_wait"] = coll
        self._round_boundary(rec, seconds, coll)
        if self._warm_round is None:
            self._warm_round = epoch
        if compiles:
            if epoch == self._warm_round:  # first round of THIS run
                self.compiles_warmup += compiles
            else:
                self.compiles_steady += compiles
                if self._steady_counter is None:
                    self._steady_counter = get_registry().counter(
                        "xtb_compiles_steady",
                        "backend compiles after warm-up (SLO: 0)",
                        ("scope",)).labels("train")
                self._steady_counter.inc(compiles)
        self.history.append(rec)
        return False

    # ------------------------------------------------------------ internals
    @staticmethod
    def _coll_sums() -> Dict[Any, Any]:
        """Current (op, rank) -> (count, seconds) of the collective-wait
        histogram (empty for single-process runs that never registered
        it)."""
        from .registry import get_registry

        hist = get_registry().get("xtb_coll_wait_seconds")
        return hist.snapshot_sums() if hist is not None else {}

    def _coll_delta(self, base: Dict[Any, Any]) -> Dict[str, float]:
        total_s, total_n = 0.0, 0
        for key, (n, s) in self._coll_sums().items():
            n0, s0 = base.get(key, (0, 0.0))
            total_s += s - s0
            total_n += n - n0
        return {"seconds": total_s, "count": int(total_n)}

    def _round_boundary(self, rec: Dict[str, Any], seconds: float,
                        coll: Dict[str, float]) -> None:
        """Distributed observability at the round boundary: flight-ring
        breadcrumb, rate-limited snapshot ship to the tracker, and the
        optional cross-rank straggler report (one extra allgather)."""
        from . import distributed, flight

        flight.record("event", "train.round", round=rec["round"],
                      seconds=seconds)
        try:
            distributed.ship_to_tracker()
        except Exception:  # pragma: no cover - shipping is best-effort
            pass
        if not self.straggler:
            return
        from .. import collective

        if not collective.is_distributed():
            return
        import numpy as np

        walls = collective.allgather(
            np.asarray([seconds, coll["seconds"]], np.float64))
        round_walls = [float(w) for w in walls[:, 0]]
        rec["straggler"] = {
            "walls": round_walls,
            "coll_wait": [float(w) for w in walls[:, 1]],
            "max_rank": int(np.argmax(walls[:, 0])),
            "min_rank": int(np.argmin(walls[:, 0])),
            "spread_s": float(max(round_walls) - min(round_walls)),
        }

    def _tree_stats(self, model) -> List[Dict[str, int]]:
        """Stats of the trees committed since the last look.  cv() hands the
        callbacks an aggregate stand-in without .trees — record nothing."""
        trees = getattr(model, "trees", None)
        if trees is None:
            return []
        out = []
        for t in trees[self._ntrees0:]:
            out.append({
                "nodes": int(t.n_nodes),
                "leaves": int(t.num_leaves),
                "depth": int(t.max_depth),
            })
        self._ntrees0 = len(trees)
        return out
