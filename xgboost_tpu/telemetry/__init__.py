"""xgboost_tpu.telemetry — unified observability for training and serving.

One subsystem replaces the three disconnected mechanisms the repo grew
(utils/timer.Monitor stderr prints, utils/observer debug dumps,
serving/metrics counters with no export format):

- **Registry** (registry.py): lock-cheap Counter/Gauge/Histogram families
  with labels; ``serving/metrics.ServingMetrics`` feeds it, the span tracer
  records into it, ``render_prometheus()`` exposes it.
- **Spans** (spans.py): ``span("grow.build_hist")`` brackets the training
  and serving hot paths — perf_counter histogram + JSONL trace event +
  jax.profiler.TraceAnnotation, all behind one enabled flag
  (``enable()`` / env ``XGBOOST_TPU_TRACE``), no-op by default.
- **Retrace accounting** (compile.py): every XLA backend compile is counted
  process-wide (``compiles_total()``, ``xtb_compiles_total``); a second
  identical train() records zero — the guard tests/test_telemetry.py keeps.
- **Exporters**: ``render_prometheus()`` text exposition and the
  chrome://tracing JSONL writer gated by ``XGBOOST_TPU_TRACE=path``
  (trace.py).
- **TelemetryCallback** (callback.py): per-round phase timings, tree
  stats, compile deltas, collective-wait attribution, and the optional
  cross-rank straggler report as an inspectable history.
- **Distributed plane** (distributed.py): workers/replicas ship registry
  snapshots over their existing channels into a driver-side
  ``MergedRegistry`` (per-``proc``-labeled + merged series) behind an
  HTTP ``/metrics`` scrape endpoint (``XGBOOST_TPU_METRICS_PORT``).
- **Flight recorder** (flight.py): always-armed fixed-size ring of recent
  spans/events/faults, dumped on crash/kill (and driver-side for
  SIGKILL'd replicas) — postmortems without tracing enabled.
- **Sampling profiler** (profiler.py): default-on wall sampler
  (``XGBOOST_TPU_PROF_HZ``, a few Hz) whose folded stacks ship with
  every telemetry payload into a driver-side merged flame view
  (``profiler.render_folded()`` — collapsed-stack format).

Quick start::

    import xgboost_tpu as xtb
    from xgboost_tpu import telemetry

    telemetry.enable()                      # or XGBOOST_TPU_TRACE=run.jsonl
    cb = telemetry.TelemetryCallback()
    xtb.train(params, dtrain, 10, callbacks=[cb])
    print(telemetry.render_prometheus())    # per-phase histograms, compiles
    cb.history[1]["phases"]                 # round 1 attribution

docs/observability.md is the guide.
"""
from __future__ import annotations

from .registry import (Counter, Gauge, Histogram, Registry, get_registry,
                       render_prometheus)
from .spans import (PHASE_HISTOGRAM, Span, disable, enable, enabled,
                    phase_totals, record_phase, span)
from .compile import COMPILE_EVENT, compile_delta, compiles_total
from . import distributed, flight, native_pool, profiler, trace
from .distributed import (MergedRegistry, get_merged, snapshot_payload,
                          start_metrics_server, stop_metrics_server)
from .callback import TelemetryCallback

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "get_registry",
    "render_prometheus",
    "span", "Span", "enable", "disable", "enabled", "record_phase",
    "phase_totals", "PHASE_HISTOGRAM",
    "compiles_total", "compile_delta", "COMPILE_EVENT",
    "trace", "native_pool", "distributed", "flight", "profiler",
    "MergedRegistry", "get_merged", "snapshot_payload",
    "start_metrics_server", "stop_metrics_server",
    "TelemetryCallback",
]
