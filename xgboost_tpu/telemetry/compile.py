"""Retrace accounting: count XLA backend compiles process-wide.

JAX emits a ``/jax/core/compile/backend_compile_duration`` monitoring event
for every program that actually reaches the backend compiler — cache hits
(in-memory jit cache or the persistent compilation cache) do not fire it.
Counting those events gives the exact signal "Out-of-Core GPU Gradient
Boosting" (2005.09148) calls out: the difference between a tuned pipeline
and an accidentally-retracing one is knowing when a step compiled.

The listener registers once at import, costs nothing between compiles, and
feeds three sinks:

- ``compiles_total()`` — the process-global int both training
  (``TelemetryCallback`` per-round deltas, steady-state SLO: 0 after the
  warm-up round) and serving (``ServingEngine`` windows) read;
- the registry counters ``xtb_compiles_total`` / ``xtb_compiles_steady``
  (the steady counter is fed by whoever owns the warm/steady boundary —
  the TelemetryCallback after round 0, ServingMetrics outside warmup());
- a JSONL trace event per compile when ``XGBOOST_TPU_TRACE`` is set, so
  retraces are visible inline with the phase spans they stall.

``jax.monitoring`` listeners cannot be unregistered individually, so this
must never be registered twice (the module guard) and must stay cheap
forever (it is: one string compare per monitoring event).
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from . import trace
from .registry import get_registry

__all__ = ["compiles_total", "compile_delta", "install", "COMPILE_EVENT"]

COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_total = 0
_installed = False
_counter = None  # xtb_compiles_total registry child (lazy)


def _on_event(name: str, duration_secs: float, **kw) -> None:
    global _total, _counter
    if name != COMPILE_EVENT:
        return
    with _lock:
        _total += 1
    if _counter is None:
        _counter = get_registry().counter(
            "xtb_compiles_total",
            "XLA backend compiles in this process (cache misses)").labels()
    _counter.inc()
    if trace.active():
        dur_ns = int(duration_secs * 1e9)
        trace.emit("xla.compile", time.perf_counter_ns() - dur_ns, dur_ns)


def install() -> None:
    """Register the monitoring listener (idempotent; called at telemetry
    import so compile counts exist before the first train())."""
    global _installed
    if _installed:
        return
    try:
        import jax.monitoring
    except Exception:  # pragma: no cover - no jax in the process
        return
    jax.monitoring.register_event_duration_secs_listener(_on_event)
    _installed = True


def compiles_total() -> int:
    """Backend compiles since process start (monotonic)."""
    return _total


class compile_delta:
    """``with compile_delta() as w: ...; w.count`` — compiles inside the
    block.  Process-global like the underlying jit caches: concurrent
    compiling threads land in whichever window is open (same best-effort
    attribution as ServingMetrics.note_steady_compiles)."""

    def __init__(self) -> None:
        self._start = 0
        self.count: Optional[int] = None

    def __enter__(self) -> "compile_delta":
        self._start = compiles_total()
        return self

    def __exit__(self, *exc) -> None:
        self.count = compiles_total() - self._start


install()
