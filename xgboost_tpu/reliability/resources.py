"""Resource governor: bounded disk/memory/fd headroom becomes *deliberate
degradation*, never an unplanned death.

Every reliability layer before this one assumed resources are infinite: a
full disk crashed a checkpoint mid-train, memory pressure OOM-killed an
external-memory run instead of shrinking its page cache, and the fleet's
only overload defense was a fixed queue bound.  The out-of-core designs
this repo reproduces (Chen & Guestrin KDD'16 §4; Out-of-Core GPU Gradient
Boosting, arXiv:2005.09148) exist precisely because resources are bounded
— so when the system hits a bound it must step down a *ladder*, one
deterministic, observable transition at a time (docs/reliability.md
"Resource pressure & graceful degradation"):

- **Levels.**  The governor tracks one small integer level (0 = nominal,
  up to :data:`MAX_LEVEL`) per resource — ``memory`` / ``disk`` / ``fd``
  / ``overload`` — published as the ``xtb_resource_level`` gauge.
  :func:`ResourceGovernor.degrade` raises a level (flight-ring event +
  stderr line), :func:`ResourceGovernor.restore` lowers it.
- **Ladders.**  Subsystems *consult* levels instead of reacting to OOM:
  external memory drops prefetch to 0 and shrinks its page LRU budget by
  :meth:`ResourceGovernor.memory_scale` (level 2+ = recompute from the
  backing store every touch); the fleet dispatcher's brownout cutoff
  (:meth:`ResourceGovernor.brownout_cutoff`) sheds low-SLO tenants first.
  Checkpoint/journal/modelstore react at their own write failures and
  report each ladder step through :func:`degraded_event`
  (``xtb_resource_degraded_total{subsystem}``).
- **Classification.**  :func:`note_os_error` is the one funnel for OS
  errors previously swallowed silently: every classified errno counts
  into ``xtb_resource_errors_total{errno,site}``, and resource-class
  errnos (ENOSPC/EDQUOT → disk, EMFILE/ENFILE → fd, ENOMEM → memory)
  additionally degrade the matching level.  The xtblint XTB801 rule
  statically forbids ``except OSError`` handlers in reliability/serving/
  data modules that neither re-raise, route through here, nor count.
- **Headroom polling.**  :meth:`ResourceGovernor.poll` measures real
  headroom (``os.statvfs`` free bytes on watched directories, free fd
  slots vs ``RLIMIT_NOFILE``) with hysteresis, publishing
  ``xtb_resource_headroom``; and it fires the ``resource.pressure``
  fault seam first, so chaos plans drive every ladder transition
  deterministically (``mem_pressure`` degrades memory; ``disk_full`` /
  ``fd_exhaust`` raise the matching OSError into the classifier) —
  no real exhaustion needed to reach any step.

Determinism contract: given the same fault plan (pressure schedule),
every ladder transition happens at the same program point, and degraded
training produces bitwise-identical model bytes to an undegraded twin —
degradation changes *how hard the machine works*, never the math
(pinned by tests/test_resources.py and the ``resource`` chaos scenario).
"""
from __future__ import annotations

import errno as _errno
import os
import threading
import time
import warnings
from typing import Any, Dict, Optional

__all__ = ["ResourceGovernor", "get_governor", "note_os_error",
           "degraded_event", "is_resource_errno", "reset", "RESOURCES",
           "MAX_LEVEL"]

RESOURCES = ("memory", "disk", "fd", "overload")
MAX_LEVEL = 3

# errno -> governed resource.  Everything else is classified (counted by
# name) but degrades nothing.
_ERRNO_RESOURCE = {
    _errno.ENOSPC: "disk",
    _errno.EDQUOT: "disk",
    _errno.EMFILE: "fd",
    _errno.ENFILE: "fd",
    _errno.ENOMEM: "memory",
}
# the disk-class set subsystem ladders key off ("is this worth a prune/
# compact retry, or a real bug to re-raise")
DISK_ERRNOS = ("ENOSPC", "EDQUOT")

# real-headroom thresholds (env-overridable); hysteresis restores at 2x
_ENV_DISK_MIN_MB = "XGBOOST_TPU_DISK_MIN_MB"        # default 64 MB free
_ENV_FD_MIN = "XGBOOST_TPU_FD_MIN"                  # default 64 free slots
_ENV_POLL_S = "XGBOOST_TPU_RESOURCE_POLL_S"         # default 1.0 s

_instruments = None


def _ins():
    """(degraded_total, errors_total, level gauge, headroom gauge)."""
    global _instruments
    if _instruments is None:
        from ..telemetry.registry import get_registry

        reg = get_registry()
        _instruments = (
            reg.counter("xtb_resource_degraded_total",
                        "graceful-degradation ladder steps taken, by "
                        "subsystem (checkpoint/journal/modelstore/extmem/"
                        "fleet)", ("subsystem",)),
            reg.counter("xtb_resource_errors_total",
                        "OS errors classified at a resource boundary, by "
                        "errno name and site (silent swallows surfaced — "
                        "xtblint XTB801)", ("errno", "site")),
            reg.gauge("xtb_resource_level",
                      "governor degradation level per resource (0 = "
                      "nominal)", ("resource",)),
            reg.gauge("xtb_resource_headroom",
                      "measured headroom per resource (disk: free bytes "
                      "on the tightest watched path; fd: free descriptor "
                      "slots)", ("resource",)),
        )
    return _instruments


def degraded_event(subsystem: str, action: str, **detail: Any) -> None:
    """One ladder step taken by ``subsystem``: counter + flight-recorder
    event + a LOUD warning.  Every graceful-degradation transition in the
    repo routes through here, so "did the system degrade, where, and why"
    is one counter family and one flight-ring query."""
    _ins()[0].labels(subsystem).inc()
    from ..telemetry import flight

    flight.record("event", "resource.degraded", subsystem=subsystem,
                  action=action, **detail)
    warnings.warn(
        f"[resource] {subsystem} degraded: {action} {detail or ''} — "
        f"continuing (see docs/reliability.md 'Resource pressure & "
        f"graceful degradation')", RuntimeWarning, stacklevel=2)


def is_resource_errno(exc: BaseException) -> bool:
    """True when the exception's errno is exhaustion-class (disk/fd/
    memory) — the branch point between "pressure: degrade and continue"
    and "bug: re-raise" that every ladder uses (a permission error is a
    bug, not pressure)."""
    return getattr(exc, "errno", None) in _ERRNO_RESOURCE


def note_os_error(exc: BaseException, site: str) -> str:
    """Classify one caught OSError: count it into
    ``xtb_resource_errors_total{errno,site}`` and degrade the matching
    governor level for resource-class errnos.  Returns the errno name
    (``"ENOSPC"``, ``"EMFILE"``, ...; ``"EUNKNOWN"`` when the exception
    carries none) so callers can branch on the class — the one funnel
    replacing silent ``except OSError: pass`` swallows (xtblint XTB801).
    """
    num = getattr(exc, "errno", None)
    name = (_errno.errorcode.get(num, f"E{num}") if num is not None
            else "EUNKNOWN")
    _ins()[1].labels(name, site).inc()
    resource = _ERRNO_RESOURCE.get(num)
    if resource is not None:
        get_governor().degrade(resource, f"{name} at {site}")
    return name


class ResourceGovernor:
    """Process-wide resource levels + headroom polling (one singleton via
    :func:`get_governor`; construct directly only in tests)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._levels: Dict[str, int] = {r: 0 for r in RESOURCES}
        self._polls = 0
        self._last_headroom = 0.0   # monotonic; gates real statvfs work
        self._below: Dict[str, bool] = {"disk": False, "fd": False}
        self.watch_paths: set = set()

    # ------------------------------------------------------------- levels
    def level(self, resource: str) -> int:
        with self._lock:
            return self._levels[resource]

    def max_level(self) -> int:
        with self._lock:
            return max(self._levels.values())

    def degraded(self) -> bool:
        return self.max_level() > 0

    def degrade(self, resource: str, reason: str) -> int:
        """Raise ``resource``'s level by one (capped at :data:`MAX_LEVEL`).
        Returns the new level.  Idempotent at the cap; every actual
        transition is an observable event."""
        with self._lock:
            old = self._levels[resource]
            new = min(old + 1, MAX_LEVEL)
            self._levels[resource] = new
        if new != old:
            _ins()[2].labels(resource).set(new)
            from ..telemetry import flight

            flight.record("event", "resource.level", resource=resource,
                          level=new, reason=reason)
            import sys

            print(f"[resource] {resource} pressure level {old} -> {new} "
                  f"({reason})", file=sys.stderr, flush=True)
        return new

    def restore(self, resource: str) -> int:
        """Lower ``resource``'s level by one (floor 0); the recovery half
        of the ladder, driven by headroom hysteresis or the caller."""
        with self._lock:
            old = self._levels[resource]
            new = max(old - 1, 0)
            self._levels[resource] = new
        if new != old:
            _ins()[2].labels(resource).set(new)
            from ..telemetry import flight

            flight.record("event", "resource.level", resource=resource,
                          level=new, reason="restored")
        return new

    # --------------------------------------------------- subsystem ladders
    def memory_scale(self) -> float:
        """Multiplier for memory budgets (the extmem page LRU cache):
        level 0 → 1.0, level 1 → 0.25, level 2+ → 0.0 (cache disabled —
        every page touch recomputes from its backing store)."""
        lvl = self.level("memory")
        if lvl <= 0:
            return 1.0
        return 0.25 if lvl == 1 else 0.0

    def prefetch_allowed(self) -> bool:
        """False under memory or fd pressure: the extmem prefetch window
        drops to 0 (no decoded pages in flight beyond the consumer, no
        extra spill files open)."""
        return self.level("memory") < 1 and self.level("fd") < 1

    def brownout_cutoff(self) -> Optional[int]:
        """SLO-priority admission cutoff for the fleet dispatcher, from
        the WORST resource level: None at level 0 (no brownout); at level
        L, requests with ``priority < L - 1`` are shed at admission —
        level 1 sheds below-default tenants (priority < 0, including
        shadow twins), level 2 sheds the default class too, level 3 only
        admits priority >= 2."""
        lvl = self.max_level()
        if lvl <= 0:
            return None
        return lvl - 1

    # ------------------------------------------------------------- polling
    def poll(self, path: Optional[str] = None) -> Dict[str, Any]:
        """One governor tick: fire the ``resource.pressure`` fault seam
        (the deterministic chaos hook — ``mem_pressure`` degrades memory;
        ``disk_full``/``fd_exhaust`` raise the matching OSError into the
        classifier), then measure real headroom (rate-limited).  Cheap to
        call from hot-ish paths: with no plan installed and within the
        poll interval it is one module read + one clock read."""
        from . import faults

        with self._lock:
            self._polls += 1
            n = self._polls
        spec = None
        try:
            spec = faults.maybe_inject("resource.pressure", round=n - 1)
        except OSError as e:
            # injected disk_full / fd_exhaust: classified exactly like a
            # real one caught at a write seam
            note_os_error(e, "resource.poll")
        if spec is not None and spec.kind == "mem_pressure":
            self.degrade("memory", "injected mem_pressure")
        if path is not None:
            self.watch_paths.add(os.fspath(path))
        now = time.monotonic()
        with self._lock:
            due = now - self._last_headroom >= self._poll_interval()
            if due:
                self._last_headroom = now
        out: Dict[str, Any] = {"polls": n}
        if due:
            out.update(self._measure_headroom())
        return out

    @staticmethod
    def _poll_interval() -> float:
        try:
            return max(0.0, float(os.environ.get(_ENV_POLL_S, "1.0")))
        except ValueError:
            return 1.0

    def _measure_headroom(self) -> Dict[str, Any]:
        """Real disk/fd headroom with hysteresis: degrade on the
        transition below the floor, restore on the transition back above
        2x the floor — repeated polls at a steady level are no-ops."""
        out: Dict[str, Any] = {}
        try:
            disk_min = float(os.environ.get(_ENV_DISK_MIN_MB, "64")) * 2**20
        except ValueError:
            disk_min = 64 * 2**20
        free = None
        for p in list(self.watch_paths) or ["."]:
            try:
                st = os.statvfs(p)
            except OSError as e:
                note_os_error(e, "resource.statvfs")
                continue
            avail = st.f_bavail * st.f_frsize
            free = avail if free is None else min(free, avail)
        if free is not None:
            out["disk_free_bytes"] = int(free)
            _ins()[3].labels("disk").set(float(free))
            self._hysteresis("disk", free, disk_min)
        try:
            fd_min = int(os.environ.get(_ENV_FD_MIN, "64"))
        except ValueError:
            fd_min = 64
        fd_free = self._fd_free()
        if fd_free is not None:
            out["fd_free"] = fd_free
            _ins()[3].labels("fd").set(float(fd_free))
            self._hysteresis("fd", fd_free, fd_min)
        return out

    def _hysteresis(self, resource: str, free: float, floor: float) -> None:
        """Degrade on the transition below ``floor``; restore while
        headroom sits at/above 2x the floor.  The latch (``_below``)
        clears ONLY at the restore point — a gradual recovery through
        the [floor, 2*floor) gray zone must not forget the dip, and a
        level raised by a *classified errno* (``note_os_error``) with no
        latch set is still walked back one step per measurement once
        real headroom says the resource is healthy again (the errno
        path has no other restore edge — without this, one transient
        ENOSPC/EMFILE would brown out low-SLO tenants for the process
        lifetime)."""
        below = free < floor
        healthy = free >= 2 * floor
        with self._lock:
            was = self._below[resource]
            if below:
                self._below[resource] = True
            elif healthy:
                self._below[resource] = False
            # in the gray zone the latch keeps its previous state
        if below and not was:
            self.degrade(resource, f"headroom {free:.0f} < floor "
                                   f"{floor:.0f}")
        elif healthy and self.level(resource) > 0:
            self.restore(resource)

    @staticmethod
    def _fd_free() -> Optional[int]:
        try:
            import resource as _rlimit

            soft, _hard = _rlimit.getrlimit(_rlimit.RLIMIT_NOFILE)
            used = len(os.listdir("/proc/self/fd"))
            return max(int(soft) - used, 0)
        except FileNotFoundError:
            return None  # no /proc: unmetered platform, not an error
        except OSError as e:
            note_os_error(e, "resource.fd_probe")
            return None
        except (ImportError, ValueError):
            return None  # platform without rlimits: unmetered

    # --------------------------------------------------------------- tests
    def reset(self) -> None:
        with self._lock:
            changed = [r for r, v in self._levels.items() if v]
            for r in RESOURCES:
                self._levels[r] = 0
            self._polls = 0
            self._last_headroom = 0.0
            self._below = {"disk": False, "fd": False}
            self.watch_paths.clear()
        for r in changed:
            _ins()[2].labels(r).set(0.0)


_GOVERNOR: Optional[ResourceGovernor] = None
_GOVERNOR_LOCK = threading.Lock()


def get_governor() -> ResourceGovernor:
    global _GOVERNOR
    if _GOVERNOR is None:
        with _GOVERNOR_LOCK:
            if _GOVERNOR is None:
                _GOVERNOR = ResourceGovernor()
    return _GOVERNOR


def reset() -> None:
    """Reset the singleton's levels/polls (test + chaos-episode isolation;
    the instance itself is kept so cached references stay valid)."""
    if _GOVERNOR is not None:
        _GOVERNOR.reset()
