"""Deterministic fault-injection harness.

Training, the collective, the tracker, and the serving batcher all expose
*seams* — named call sites (``train.round``, ``collective.allreduce``,
``tracker.connect``, ``tracker.connected``, ``process.allreduce``,
``checkpoint.write``, ``serve.worker``) that consult an installed
:class:`FaultPlan` before doing their real work.  A plan is a list of fault
specs, each matching a seam by name plus optional ``rank`` / ``round`` /
``at`` (the Nth invocation of that seam in this process) and firing at most
``times`` times.  Because every matcher is an explicit value and invocation
counters advance with program order, a plan replays identically run after
run — the property the kill/resume parity tests rely on.

Kinds:

- ``kill``       — ``os._exit(exit_code)``: a hard worker death (SIGKILL
  moral equivalent; no finalizers, no tracker shutdown message).
- ``exception``  — raise :class:`FaultInjected` at the seam.
- ``delay``      — sleep ``seconds`` then continue (slow-peer simulation).
- ``drop_connection`` / ``truncate`` — returned to the caller, which owns
  the resource being damaged (the tracker client closes its socket, the
  checkpoint writer truncates the file).
- ``corrupt``    — returned to the caller, which flips bytes in the
  payload it owns (:func:`corrupt_bytes`): a wire frame, an extmem page
  decode, a model arena, a checkpoint file.  The flip is a deterministic
  function of (spec, payload length) — ``offset`` (default: the middle
  byte) XORed with ``xor_mask`` — so a corruption episode replays
  bit-for-bit.  The integrity layer (docs/reliability.md "Integrity &
  chaos") must *detect* every one: checksum-verify, quarantine or retry,
  never decode garbage.

Resource-class kinds (docs/reliability.md "Resource pressure & graceful
degradation" — the deterministic stand-ins for a machine running out of
something; each must land on a *degradation ladder*, never a crash):

- ``disk_full``   — raise ``OSError(ENOSPC)`` at the seam, exactly what
  a full disk makes the next ``write()``/``fsync()`` do.  Checkpoint
  saves prune-and-retry then skip with a loud warning; journal appends
  force a compaction; a model-store publish fails the lifecycle cycle
  cleanly (incumbent untouched).
- ``fd_exhaust``  — raise ``OSError(EMFILE)``: the process is out of
  file descriptors.
- ``slow_disk``   — sleep ``seconds`` then continue: a degraded device
  (like ``delay``, but classified as a resource fault so plans read
  honestly).
- ``mem_pressure`` — returned to the caller: the resource governor
  (``reliability/resources.py``) shrinks its enforced memory budget one
  level (extmem prefetch off, page LRU cache cut).  Fired at the
  ``resource.pressure`` seam the governor polls.

Network-degradation kinds (docs/reliability.md "Degraded networks" — the
deterministic stand-ins for gray links: slow, shaped, or half-open, never
cleanly dead; each must be *survived*, not just detected):

- ``latency``      — sleep a per-invocation jitter sampled in
  ``[0, seconds]`` from a seeded hash of ``(jitter_seed, invocation)``
  (:func:`jitter_seconds`), applied at the seam like ``delay`` but
  different every frame and identical every replay.
- ``throttle``     — returned to the caller, which owns the bytes being
  sent: sleep ``nbytes / bytes_per_s`` (:func:`throttle_seconds`) before
  the write, shaping the link's effective bandwidth.
- ``blackhole_tx`` — returned to the caller at a *send* seam: the bytes
  silently vanish (the write is skipped, the connection stays open) — the
  outbound half of a half-open link.  The peer sees silence, not EOF.
- ``blackhole_rx`` — returned to the caller at a *receive* seam
  (``wire.recv`` / ``tracker.recv``): the caller reads a full frame and
  discards it, so inbound data is consumed by the kernel but never
  delivered up the stack — the inbound half of a half-open link.
- ``partition``    — returned to the caller at either socket seam: a
  seeded bipartition of ranks/replicas (:func:`partition_blocks` — a pure
  hash of ``(jitter_seed, peer)``).  Links whose peer lands on the cut
  side behave as blackholed in the seam's direction; because the send and
  receive seams consult the same predicate independently, one seed yields
  *asymmetric* partitions (a rank whose tx is cut but rx is not).

Plans install programmatically (``install(...)``) or through the
``XGBOOST_TPU_FAULT_PLAN`` environment variable — either inline JSON or a
path to a JSON file — so spawned worker subprocesses inherit the plan with
no extra wiring.  With no plan installed every seam is a single module-
attribute check.

Every fired fault counts into ``xtb_faults_injected_total{site,kind}``
(telemetry registry), so a test can assert not just the failure's effect
but that the harness — not an unrelated bug — caused it.

``SEAMS`` is the canonical seam set (checked statically by xtblint's
XTB3xx rules against every call site and docs/reliability.md); setting
``XGBOOST_TPU_STRICT_SEAMS=1`` additionally rejects unknown seam names at
runtime, both at the seam and at plan-install time.
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Union

__all__ = ["FaultInjected", "FaultSpec", "FaultPlan", "install", "clear",
           "active", "maybe_inject", "corrupt_bytes", "jitter_seconds",
           "throttle_seconds", "partition_blocks", "ENV_VAR", "SEAMS",
           "STRICT_ENV"]

ENV_VAR = "XGBOOST_TPU_FAULT_PLAN"

# The canonical seam set — the single source of truth the static analyzer
# (xtblint XTB3xx) checks every maybe_inject() call site and the
# docs/reliability.md seam table against.  Adding a seam means adding it
# here, at the call site, and in the docs — the linter fails the gate on
# any one of the three drifting.
SEAMS = frozenset({
    "train.round",
    "collective.allreduce",
    "collective.allgather",
    "collective.regroup",
    "process.allreduce",
    "tracker.connect",
    "tracker.connected",
    "tracker.regroup",
    "tracker.message",
    "checkpoint.write",
    "serve.worker",
    "fleet.dispatch",
    "native.parallel_for",
    "lifecycle.validate",
    "lifecycle.swap",
    "extmem.page_load",
    "extmem.page_decode",
    "wire.frame",
    "wire.recv",
    "tracker.recv",
    "modelstore.publish",
    "tracker.journal",
    "watchdog.escalate",
    "resource.pressure",
    "online.sample",
    "online.label_join",
    "online.retrain",
})

# Debug guard: with XGBOOST_TPU_STRICT_SEAMS=1, maybe_inject() rejects
# seam names outside SEAMS and plans naming unknown sites fail at install
# time — the runtime complement of the static XTB3xx check (catches seams
# constructed dynamically, which the linter cannot see).
STRICT_ENV = "XGBOOST_TPU_STRICT_SEAMS"
_STRICT: Optional[bool] = None

_KINDS = ("kill", "exception", "delay", "drop_connection", "truncate",
          "corrupt", "disk_full", "mem_pressure", "fd_exhaust", "slow_disk",
          "latency", "throttle", "blackhole_rx", "blackhole_tx", "partition")


def _strict() -> bool:
    global _STRICT
    if _STRICT is None:
        _STRICT = os.environ.get(STRICT_ENV, "").strip() not in ("", "0")
    return _STRICT


def _check_sites(specs) -> None:
    """Strict-mode seam validation for every plan path (construction AND
    install — a plan built while strict was off must not slip through)."""
    if not _strict():
        return
    for spec in specs:
        if spec.site not in SEAMS:
            raise ValueError(
                f"unknown fault seam {spec.site!r} (strict mode); "
                f"known seams: {sorted(SEAMS)}")


class FaultInjected(RuntimeError):
    """Raised at a seam by an ``exception`` fault spec."""


@dataclasses.dataclass
class FaultSpec:
    """One planned fault.  ``site`` and ``kind`` are required; the rest are
    matchers/parameters (``None`` = match any)."""

    site: str
    kind: str
    rank: Optional[int] = None       # fire only on this worker rank
    round: Optional[int] = None      # fire only at this training round
    at: Optional[int] = None         # fire only on the Nth seam hit (0-based)
    times: int = 1                   # fire at most this many times
    seconds: float = 0.0             # delay duration
    exit_code: int = 43              # kill exit status
    keep_bytes: Optional[int] = None  # truncate: bytes to keep (None = half)
    offset: Optional[int] = None     # corrupt: byte offset (None = middle)
    xor_mask: int = 0xFF             # corrupt: XOR applied to the byte
    jitter_seed: int = 0             # latency/partition: determinism seed
    bytes_per_s: float = 0.0         # throttle: shaped link bandwidth
    message: str = "injected fault"

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"expected one of {_KINDS}")

    def matches(self, invocation: int, rank: Optional[int],
                round: Optional[int]) -> bool:
        if self.at is not None and invocation != self.at:
            return False
        if self.round is not None and round != self.round:
            return False
        if self.rank is not None and rank != self.rank:
            return False
        return True


class FaultPlan:
    """An installed set of :class:`FaultSpec` plus per-site invocation and
    per-spec trigger counters (all process-local, lock-guarded)."""

    def __init__(self, specs: List[FaultSpec]) -> None:
        self.specs = list(specs)
        _check_sites(self.specs)
        self._fired: Dict[int, int] = {}    # spec index -> times fired
        self._calls: Dict[str, int] = {}    # site -> invocation counter
        self._lock = threading.Lock()

    @classmethod
    def from_dict(cls, obj: Union[dict, list]) -> "FaultPlan":
        raw = obj.get("faults", []) if isinstance(obj, dict) else obj
        specs = []
        for f in raw:
            known = {fld.name for fld in dataclasses.fields(FaultSpec)}
            unknown = set(f) - known
            if unknown:
                raise ValueError(f"unknown fault-spec keys {sorted(unknown)}")
            specs.append(FaultSpec(**f))
        return cls(specs)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls.from_dict(json.loads(text))

    def fired(self, site: Optional[str] = None) -> int:
        """Total faults fired (optionally only at ``site``)."""
        with self._lock:
            return sum(n for i, n in self._fired.items()
                       if site is None or self.specs[i].site == site)

    def fired_by_spec(self) -> List[tuple]:
        """``[(spec, times_fired), ...]`` in plan order — the chaos
        harness's post-episode ledger (which planned faults actually hit,
        so invariants like "deaths == severed connections" can be checked
        against what fired, not what was merely scheduled)."""
        with self._lock:
            return [(spec, self._fired.get(i, 0))
                    for i, spec in enumerate(self.specs)]

    def _claim(self, site: str, rank, round):
        """Match-and-count under the lock; returns ``(spec, invocation)``
        to fire (the invocation index seeds per-frame jitter) or None."""
        with self._lock:
            inv = self._calls.get(site, 0)
            self._calls[site] = inv + 1
            for i, spec in enumerate(self.specs):
                if spec.site != site:
                    continue
                if self._fired.get(i, 0) >= spec.times:
                    continue
                if spec.matches(inv, rank, round):
                    self._fired[i] = self._fired.get(i, 0) + 1
                    return spec, inv
        return None


# ---------------------------------------------------------------------------
# module-level installation (env-driven or programmatic)
# ---------------------------------------------------------------------------

_PLAN: Optional[FaultPlan] = None
_ENV_CHECKED = False
_counter = None  # xtb_faults_injected_total family, created lazily
# lockdep witness hook: lockdep.install() points this at note_seam so a
# lock held across any fault seam is reported (runtime XTB902); None —
# one global read per maybe_inject — when the witness is unarmed
_lockdep_seam = None


def install(plan: Union[FaultPlan, dict, list, str, None]) -> Optional[FaultPlan]:
    """Install a fault plan process-wide (dict/list/JSON accepted); ``None``
    clears.  Returns the installed :class:`FaultPlan`."""
    global _PLAN, _ENV_CHECKED
    if plan is None:
        _PLAN = None
    elif isinstance(plan, FaultPlan):
        _check_sites(plan.specs)
        _PLAN = plan
    elif isinstance(plan, str):
        _PLAN = FaultPlan.from_json(plan)
    else:
        _PLAN = FaultPlan.from_dict(plan)
    _ENV_CHECKED = True  # programmatic install wins over the env var
    return _PLAN


def clear() -> None:
    """Remove the installed plan AND forget the env var was consumed, so a
    test that mutates ``XGBOOST_TPU_FAULT_PLAN`` (or the strict-seams
    flag) gets a fresh load."""
    global _PLAN, _ENV_CHECKED, _STRICT
    _PLAN = None
    _ENV_CHECKED = False
    _STRICT = None


def active() -> Optional[FaultPlan]:
    """The installed plan, loading ``XGBOOST_TPU_FAULT_PLAN`` on first use
    (inline JSON, or a path to a JSON file).  None when fault injection is
    off — the common case, and the only cost every seam pays."""
    global _PLAN, _ENV_CHECKED
    if _PLAN is None and not _ENV_CHECKED:
        _ENV_CHECKED = True
        raw = os.environ.get(ENV_VAR, "").strip()
        if raw:
            if not raw.lstrip().startswith(("{", "[")):
                with open(raw) as fh:
                    raw = fh.read()
            _PLAN = FaultPlan.from_json(raw)
    return _PLAN


def _count(site: str, kind: str) -> None:
    global _counter
    if _counter is None:
        from ..telemetry.registry import get_registry

        _counter = get_registry().counter(
            "xtb_faults_injected_total", "faults fired by the injection "
            "harness", ("site", "kind"))
    _counter.labels(site, kind).inc()
    from ..telemetry import flight

    # every fired fault lands in the postmortem ring (a killed process's
    # dump then names the seam that killed it)
    flight.record("fault", site, fault_kind=kind)


def maybe_inject(site: str, *, rank: Any = None, round: Optional[int] = None,
                 ) -> Optional[FaultSpec]:
    """Seam entry point.  ``rank`` may be an int or a zero-arg callable
    (resolved only when some spec for this site constrains rank, so seams
    can pass ``collective.get_rank`` without paying for it when unused).
    Applies ``kill``/``exception``/``delay``/``slow_disk``/``latency``
    here and raises the matching ``OSError`` for ``disk_full`` (ENOSPC) /
    ``fd_exhaust`` (EMFILE); returns the spec for caller-applied kinds
    (``drop_connection``, ``truncate``, ``corrupt``, ``mem_pressure``,
    ``throttle``, ``blackhole_rx``, ``blackhole_tx``, ``partition``)
    and for ``delay``/``slow_disk``/``latency`` (so callers can log),
    else None."""
    if _lockdep_seam is not None:
        _lockdep_seam(site)
    if _strict() and site not in SEAMS:
        raise ValueError(f"unknown fault seam {site!r} (strict mode); "
                         f"known seams: {sorted(SEAMS)}")
    plan = _PLAN  # fast path: installed-plan check is one global read
    if plan is None:
        plan = active()
        if plan is None:
            return None
    if callable(rank) and any(s.site == site and s.rank is not None
                              for s in plan.specs):
        rank = rank()
    elif callable(rank):
        rank = None
    claimed = plan._claim(site, rank, round)
    if claimed is None:
        return None
    spec, invocation = claimed
    _count(site, spec.kind)
    if spec.kind == "kill":
        import sys

        print(f"[faults] kill at {site} (rank={rank} round={round}): "
              f"{spec.message}", file=sys.stderr, flush=True)
        try:
            # os._exit skips atexit: flush the flight ring NOW so the
            # launcher/fleet postmortem has this process's last moments —
            # and an all-thread stack dump, so the postmortem shows what
            # every OTHER thread was doing when this one died
            from ..telemetry import flight

            flight.dump_stacks()
            flight.dump()
        except Exception:
            pass
        os._exit(spec.exit_code)
    if spec.kind == "exception":
        raise FaultInjected(f"{site}: {spec.message}")
    if spec.kind in ("delay", "slow_disk"):
        time.sleep(spec.seconds)
    elif spec.kind == "latency":
        time.sleep(jitter_seconds(spec, invocation))
    elif spec.kind == "disk_full":
        import errno

        raise OSError(errno.ENOSPC,
                      f"injected disk full at {site}: {spec.message}")
    elif spec.kind == "fd_exhaust":
        import errno

        raise OSError(errno.EMFILE,
                      f"injected fd exhaustion at {site}: {spec.message}")
    return spec


def corrupt_bytes(data, spec: FaultSpec) -> bytes:
    """Apply a ``corrupt``-kind spec to a payload: XOR one byte at
    ``spec.offset`` (``None`` = the middle byte; offsets wrap) with
    ``spec.xor_mask``.  A pure function of (payload, spec), so the same
    plan damages the same bit every replay.  A zero-effective mask falls
    back to ``0xFF`` — an installed corrupt spec must never be a no-op."""
    buf = bytearray(data)
    if not buf:
        return bytes(buf)
    off = (len(buf) // 2) if spec.offset is None else int(spec.offset)
    mask = (int(spec.xor_mask) & 0xFF) or 0xFF
    buf[off % len(buf)] ^= mask
    return bytes(buf)


def jitter_seconds(spec: FaultSpec, invocation: int) -> float:
    """Per-invocation latency sample in ``[0, spec.seconds)`` for a
    ``latency``-kind spec: a pure hash of ``(jitter_seed, invocation)``,
    so frame N of a replay jitters by exactly what frame N jittered by
    last run — no ambient RNG, no shared state."""
    h = zlib.crc32(f"{int(spec.jitter_seed)}:{int(invocation)}".encode())
    return float(spec.seconds) * ((h & 0xFFFFFF) / float(1 << 24))


def throttle_seconds(spec: FaultSpec, nbytes: int) -> float:
    """Shaping delay for ``nbytes`` under a ``throttle``-kind spec's
    ``bytes_per_s`` link budget.  The caller (which owns the socket)
    sleeps this long before the write — a pure function, so a shaped
    transfer replays with identical pacing.  A non-positive rate shapes
    nothing (0.0) rather than dividing by zero."""
    rate = float(spec.bytes_per_s)
    if rate <= 0.0:
        return 0.0
    return float(nbytes) / rate


def partition_blocks(spec: FaultSpec, peer: Any) -> bool:
    """Whether ``peer`` (a rank int or replica label) lands on the cut
    side of a ``partition``-kind spec's seeded bipartition: the parity of
    a pure hash of ``(jitter_seed, peer)``.  Send and receive seams call
    this independently with the same seed, so one spec yields asymmetric
    partitions — a peer whose hash cuts its tx seam but not its rx seam
    is exactly the half-open wedge the scenario wants.  The hash covers
    the spec's ``site`` too, so two specs sharing one seed (one at a send
    seam, one at a receive seam) cut independent sides.  ``None`` (peer
    unknown at this seam) never blocks."""
    if peer is None:
        return False
    h = zlib.crc32(f"{int(spec.jitter_seed)}:{spec.site}:{peer}".encode())
    return bool(h & 1)
